"""Asynchronous host->device input staging: uint8 wire + buffered ring.

The round-5 bench pinned the real-data ResNet point at 6.2% of synthetic
throughput and attributed the whole gap to ingest: the host pipeline
produced 2053 MB/s but serial f32 `device_put` moved ~52 MB/s against a
361 MB/s parity requirement. This module is the classic training-stack
answer, in two coordinated layers:

  1. **Wire format** (`to_wire`, `make_preprocess_fn`): ship images
     host->device as uint8 and cast/normalize on device *inside* the
     jitted step, where the cast fuses into the first conv's input read.
     4x fewer bytes on the wire drops the parity bar by 4x. Token batches
     (int32, already minimal) pass through the same API unchanged.

  2. **Staging ring** (`stage_to_device`): K device-batch slots fed by a
     pool of N background transfer *lanes* (round 11; one lane = the
     round-7 ring), so the transfer of batch N+1 overlaps the compute of
     batch N and — with multiple lanes — transfers of several batches
     overlap each other. The ring bounds in-flight device memory to K
     staged batches (+1 being consumed): a slot frees when the consumer
     takes the next batch, and XLA's allocator recycles the freed arrays'
     pages for the next transfer. Transfers can be *chunked* — split along
     the batch dim into C concurrent `device_put` calls reassembled
     on-device — which raises the effective rate on links where a single
     serial put can't fill the pipe (the tunnel, PCIe with small copies).
     Lanes pull host batches through one ordered reader (each take tagged
     with a sequence number) and deposit finished slots into an ordered
     reassembly buffer, so the consumer sees the EXACT batch order however
     the lanes race. `autotune_staging` micro-probes {lanes x chunks}
     against the live link and returns the best combination plus the full
     probe table (the trainer's `--staging-tune`).

  3. **Wire codecs** (`encode_batch`/`decode_batch`, round 11): an
     optional lossless compression layer on the wire — stdlib zlib at
     speed-biased level 1 (the lz4-ish point of the zlib dial). The
     producer leg compresses each large leaf, the lane decompresses on
     the HOST side immediately before its `device_put` (there is no
     on-device inflate), so the device math is bit-identical to the
     uncompressed wire. On a single-host runtime the codec only *costs*
     CPU — `device_put` still moves raw bytes — but the accounting
     (`bytes_encoded`, `encode_s`/`decode_s`, `codec_ratio`) measures
     exactly what a compressed remote-reader/tunnel wire protocol would
     save vs what the codec burns, which is the decision input the
     on-chip round needs (52 MB/s measured link vs codec MB/s + ratio).

Accounting is explicit (the bench reports numbers, not assertions):
`transfer_mb_per_s` from the lanes' own put timers — bytes over the
UNION of wire-busy intervals (`transfer_busy_s`), so concurrent lanes
report the effective link rate, not a per-lane average — and
`input_overlap_fraction` — the share of steady-state input seconds that
hid under compute — from stamps that telescope exactly to the consumer's
wall-clock (wall_s == consumer_wait_s + consumer_busy_s by construction,
which tests verify against a synthetic slow producer).

Thread discipline (the PR-2 invariant, now PER-LANE and pinned by test):
a lane thread only ever calls `device_put` — never `jnp.concatenate` or
any other traced program — because two threads dispatching programs onto
a multi-device mesh interleave their collectives per-device and deadlock.
Chunk reassembly therefore always runs on the consumer thread.

Normalization math is defined ONCE here (multiply by a f32-rounded
reciprocal) and used by both the host-side f32 wire path and the
on-device preprocess hook: `--wire-dtype f32` and `--wire-dtype uint8`
trajectories agree to FMA-contraction rounding (XLA fuses the mul-sub
where numpy rounds twice; the CPU parity test pins the divergence at
rtol 1e-3 over 6 optimizer steps, 1e-4 on the first). Staged vs prefetch
ingest of the SAME wire — identical device ops — IS bit-identical, and
tested as exact equality.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from tf_operator_tpu import telemetry
from tf_operator_tpu.data.prefetch import overlap_efficiency

# f32-rounded reciprocal, multiplied (not divided) on BOTH host and device:
# the same IEEE single-precision ops in the same order keeps the uint8-wire
# and f32-wire trajectories together up to XLA's FMA contraction of the
# mul-sub (the one rounding difference the parity test bounds).
U8_SCALE = np.float32(1.0) / np.float32(127.5)
U8_SHIFT = np.float32(1.0)

WIRE_DTYPES = ("auto", "uint8", "f32")

# Batch keys carrying images (the arrays the uint8 wire + on-device
# normalize applies to). uint8 elsewhere — labels under 256 classes,
# 0/1 masks — is DATA, not pixels: normalizing it would corrupt it
# (float class indices crash take_along_axis; a {-1, -0.99} mask
# silently wrecks the loss). Every model entry in models/train.py uses
# "x" for its image tensor; extend here if that contract grows.
IMAGE_KEYS = ("x",)


def normalize_uint8(x):
    """uint8 pixels -> f32 in [-1, 1], on whichever backend `x` lives.

    jnp arrays normalize on device (fused into the consuming op); numpy
    arrays normalize on host (the f32 wire path) with the identical
    constant and op order.
    """
    if isinstance(x, np.ndarray):
        return x.astype(np.float32) * U8_SCALE - U8_SHIFT
    import jax.numpy as jnp

    return x.astype(jnp.float32) * U8_SCALE - U8_SHIFT


def to_wire(batch: dict, wire_dtype: str = "auto",
            image_keys: tuple[str, ...] = IMAGE_KEYS) -> dict:
    """Host-side wire-format conversion of one dict batch. Only
    `image_keys` entries are ever converted — uint8 labels/masks are data
    and pass through under every wire dtype.

    auto  — ship every array as stored (uint8 stays uint8: the cheap wire).
    uint8 — contract check: image keys must already be uint8 (storing f32
            and quantizing here would silently lose data); everything
            else (labels, tokens, masks) passes through.
    f32   — normalize uint8 image keys to f32 ON HOST (the 4x-wider wire,
            kept as the parity reference for the on-device cast).
    """
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(f"wire_dtype {wire_dtype!r} not in {WIRE_DTYPES}")
    if wire_dtype == "auto":
        return batch
    out = {}
    for k, v in batch.items():
        if k not in image_keys:
            out[k] = v
        elif wire_dtype == "f32" and v.dtype == np.uint8:
            out[k] = normalize_uint8(v)
        elif wire_dtype == "uint8" and np.issubdtype(v.dtype, np.floating):
            raise ValueError(
                f"--wire-dtype uint8 needs uint8-stored images, but key "
                f"{k!r} is {v.dtype} — re-shard the dataset as uint8 or "
                f"use --wire-dtype auto/f32"
            )
        else:
            out[k] = v
    return out


def make_preprocess_fn(
    image_keys: tuple[str, ...] = IMAGE_KEYS,
) -> Callable[[dict], dict]:
    """On-device batch preprocessor for the train step's preprocess hook:
    normalizes uint8 IMAGE entries (the uint8 wire) and passes everything
    else (tokens, labels, masks, already-f32 images) through — uint8
    outside `image_keys` is data, never pixels. Traced into the jitted
    step, so the cast/normalize fuses with the first consumer of the
    batch and never materializes a second f32 copy in the host->device
    path."""
    import jax.numpy as jnp

    def preprocess(batch):
        return {
            k: normalize_uint8(v)
            if k in image_keys and v.dtype == jnp.uint8 else v
            for k, v in batch.items()
        }

    return preprocess


WIRE_CODECS = ("none", "zlib")

# Leaves under this size ship uncompressed whatever the codec: a label
# vector is a few hundred bytes — zlib headers + a dict hop cost more
# than the wire saves.
MIN_ENCODE_BYTES = 1 << 10

# Speed-biased deflate: level 1 is the "lz4-style" point of the zlib
# dial — on uint8 image batches it compresses within a few percent of
# level 6 at several times the throughput, and the codec rides the
# transfer path where MB/s is the whole point.
_ZLIB_LEVEL = 1


class Encoded:
    """One array leaf as it would cross a compressed wire: the codec
    payload plus the dtype/shape needed to reinflate it host-side.
    Deliberately NOT a pytree container (jax.tree.map leaf)."""

    __slots__ = ("payload", "dtype", "shape", "codec", "raw_nbytes")

    def __init__(self, payload: bytes, dtype, shape, codec: str,
                 raw_nbytes: int):
        self.payload = payload
        self.dtype = dtype
        self.shape = shape
        self.codec = codec
        self.raw_nbytes = raw_nbytes

    @property
    def nbytes(self) -> int:
        return len(self.payload)


def encode_batch(batch: dict, codec: str) -> dict:
    """Host-side wire compression of one dict batch: every array leaf at
    or over MIN_ENCODE_BYTES becomes an `Encoded` payload; small leaves
    pass through raw. Lossless for ANY dtype (bytes round-trip exactly),
    so unlike `to_wire` it needs no image-key contract."""
    if codec not in WIRE_CODECS:
        raise ValueError(f"wire codec {codec!r} not in {WIRE_CODECS}")
    if codec == "none":
        return batch
    import zlib

    out = {}
    for k, v in batch.items():
        if getattr(v, "nbytes", 0) < MIN_ENCODE_BYTES:
            out[k] = v
            continue
        out[k] = Encoded(
            zlib.compress(np.ascontiguousarray(v).tobytes(), _ZLIB_LEVEL),
            v.dtype, v.shape, codec, v.nbytes,
        )
    return out


def decode_batch(batch: dict) -> dict:
    """Inflate `Encoded` leaves back to the exact source arrays — the
    host side of the wire, immediately before the lane's device_put."""
    import zlib

    out = {}
    for k, v in batch.items():
        if isinstance(v, Encoded):
            out[k] = np.frombuffer(
                zlib.decompress(v.payload), dtype=v.dtype
            ).reshape(v.shape)
        else:
            out[k] = v
    return out


def encoded_nbytes(batch: dict) -> int:
    """Wire bytes of an encoded batch (codec payloads + raw small leaves)."""
    return sum(v.nbytes for v in batch.values())


class _Chunks:
    """Opaque holder for one array staged as C chunk transfers, awaiting
    consumer-side reassembly. Deliberately NOT a pytree container, so
    jax.tree.map treats it as a leaf."""

    __slots__ = ("parts",)

    def __init__(self, parts):
        self.parts = parts


# Arrays under this size transfer in ONE put regardless of the chunks
# knob: a label/mask vector is a few KB — splitting it buys nothing and
# multiplies per-put dispatch overhead.
MIN_CHUNK_BYTES = 1 << 20


def _dim0_shards(sharding, shape) -> int:
    """How many pieces the sharding splits dim 0 into (1 when unsharded or
    unanswerable) — each chunk's leading dim must stay divisible by this."""
    if sharding is None or not shape:
        return 1
    try:
        return shape[0] // sharding.shard_shape(tuple(shape))[0]
    except Exception:  # noqa: BLE001 — exotic shardings: just don't chunk
        return 0


def effective_chunks(x, sharding=None, chunks: int = 1) -> int:
    """Largest feasible chunk count <= requested for THIS array: chunking
    is a transfer-rate knob, not semantics, so infeasible configs degrade
    instead of erroring — the requested count may not divide the leading
    dim, and each chunk must itself remain shardable over the mesh's data
    axes (a 24-row batch on dp=8 works unchunked but no 4-way split of it
    leaves rows divisible by 8)."""
    if (chunks <= 1 or x.ndim == 0 or x.shape[0] < chunks
            or x.nbytes < MIN_CHUNK_BYTES):
        return 1
    nsh = _dim0_shards(sharding, x.shape)
    if nsh == 0:
        return 1
    for c in range(chunks, 1, -1):
        if x.shape[0] % c == 0 and (x.shape[0] // c) % nsh == 0:
            return c
    return 1


def _put_chunks(x, sharding=None, chunks: int = 1, strict: bool = False):
    """TRANSFERS ONLY — safe from a background thread.

    device_put is async: issuing C smaller puts along the leading dim lets
    the transfers stream back-to-back instead of serializing behind one
    large copy, raising the effective rate on links a single put can't
    fill. Returns a _Chunks awaiting reassembly, or a plain array when the
    chunk count resolves to 1.

    strict=False (the staging ring): chunking degrades per-array via
    effective_chunks — a perf knob must not crash the transfer thread.
    strict=True (the explicit chunked_device_put API, benchmarks/tests):
    chunk exactly as asked, raising a clear error on an infeasible split.
    """
    import jax

    def put(v):
        return jax.device_put(v, sharding) if sharding is not None \
            else jax.device_put(v)

    if strict and chunks > 1:
        if x.ndim == 0 or x.shape[0] < chunks:
            chunks = 1  # nothing to split — documented fallback
        elif x.shape[0] % chunks:
            raise ValueError(
                f"chunks {chunks} does not divide leading dim {x.shape[0]}"
            )
        else:
            nsh = _dim0_shards(sharding, x.shape)
            if nsh == 0 or (nsh > 1 and (x.shape[0] // chunks) % nsh):
                raise ValueError(
                    f"chunks {chunks} leaves {x.shape[0] // chunks}-row "
                    f"chunks the sharding cannot split over its {nsh} "
                    f"dim-0 shards"
                )
    else:
        chunks = effective_chunks(x, sharding, chunks)
    if chunks <= 1:
        return put(x)
    step = x.shape[0] // chunks
    return _Chunks([put(x[i * step:(i + 1) * step]) for i in range(chunks)])


def _assemble(tree, sharding=None):
    """Consumer-side chunk reassembly: jnp.concatenate COMPILES A PROGRAM,
    and on a multi-device mesh concurrently dispatched programs can enqueue
    their collectives in different per-device orders and deadlock — so
    reassembly must run on the thread that also dispatches the train step
    (one dispatch order), never on the transfer thread. The transfer thread
    only ever calls device_put (no program), which the prefetcher already
    proved safe."""
    import jax
    import jax.numpy as jnp

    def join(leaf):
        if not isinstance(leaf, _Chunks):
            return leaf
        out = jnp.concatenate(leaf.parts, axis=0)
        # Re-pin the step's expected batch sharding: the concat output's
        # layout is XLA's choice, and jit(in_shardings=...) rejects
        # mismatched committed arrays rather than resharding them.
        return jax.device_put(out, sharding) if sharding is not None else out

    return jax.tree.map(join, tree)


def chunked_device_put(x, sharding=None, chunks: int = 1):
    """Single-thread convenience: chunked transfer + immediate reassembly
    (tools/exp_transfer.py and tests) — STRICT: chunks exactly as asked or
    raises, so a benchmark never silently measures the unchunked path. The
    staging ring itself degrades gracefully instead and keeps the two
    phases on their proper threads — see _put_chunks/_assemble."""
    return _assemble(_put_chunks(x, sharding, chunks, strict=True), sharding)


def transfer_mb_per_s(stats: dict) -> float | None:
    """Effective host->device transfer rate from the lanes' own put
    timers: bytes actually moved through device_put over the UNION of
    wire-busy intervals (`transfer_busy_s` — seconds during which at
    least one lane sat in its transfer leg). Lane-seconds (`transfer_s`)
    would under-report a multi-lane engine by up to the lane count; the
    single-lane case is identical under both clocks. Falls back to
    `transfer_s` for stats dicts predating the union clock."""
    s = stats.get("transfer_busy_s") or stats.get("transfer_s", 0.0)
    b = stats.get("bytes_staged", 0)
    if s <= 0 or b <= 0:
        return None
    return b / 1e6 / s


def input_overlap_fraction(stats: dict) -> float | None:
    """Share of the steady-state input path (host production + wire cast/
    codec + transfer) that hid under compute. Same estimator as
    prefetch.overlap_efficiency — the ring populates the identical keys —
    but its steady_input_s denominator is the UNION of lane input-leg
    intervals windowed to the consumer's steady state, so concurrent
    lanes don't count multiply (a single-lane ring reduces to prefetch's
    per-batch sum and the two pipelines' numbers stay directly
    comparable)."""
    return overlap_efficiency(stats)


def stage_to_device(
    it: Iterator[Any],
    depth: int = 2,
    sharding=None,
    chunks: int = 1,
    wire_dtype: str = "auto",
    stats: dict | None = None,
    lanes: int = 1,
    codec: str = "none",
) -> Iterator[Any]:
    """Wrap a host-batch iterator; yields batches staged on device through
    a ring of `depth` slots fed by a pool of `lanes` transfer threads.

    depth      — ring size K: how many batches may be device-resident ahead
                 of the consumer (2 = classic double buffering). In-flight
                 device memory is bounded by K staged (+1 being consumed),
                 however many lanes feed the ring (each lane holds a slot
                 permit for the batch it is transferring).
    sharding   — optional jax.sharding.Sharding for the put (multi-process
                 jobs assemble the global batch from local slices, like
                 prefetch_to_device).
    chunks     — concurrent device_put transfers per array, degraded
                 per-array to the largest feasible count (effective_chunks:
                 size threshold, leading-dim and shard divisibility) and
                 NOT applied on the multi-process global-assembly path
                 (sharding given AND process_count > 1 — that path owns
                 its transfers); stats records the applied value as
                 chunks_effective so reported numbers never claim chunking
                 that didn't happen.
    wire_dtype — host-side wire conversion (see to_wire). On-device
                 normalization of the uint8 wire is the train step's
                 preprocess hook, not the stager's job.
    lanes      — transfer threads issuing device_puts CONCURRENTLY.
                 Batches keep their exact order: one locked reader tags
                 each host batch with a sequence number, lanes deposit
                 finished slots into an ordered buffer, and the consumer
                 takes sequence k before k+1 — whatever order the lanes
                 finish in. Degraded to min(lanes, depth) (an extra lane
                 could never hold a slot) and to 1 on the multi-process
                 global-assembly path; stats records lanes_effective.
    codec      — lossless wire compression (WIRE_CODECS; "none" default).
                 Encoded on the producer leg, decoded HOST-side by the
                 lane immediately before its device_put — the device math
                 is bit-identical to the uncompressed wire. See the
                 module docstring for what this measures on a single host.
    stats      — optional dict updated IN PLACE while the iterator is live:
        batches_staged   — batches the lanes finished transferring
        bytes_staged     — wire bytes moved host->device (decoded)
        bytes_encoded    — codec payload bytes (what a compressed remote
                           wire would carry; 0 under codec "none")
        host_s           — lane-seconds in next(it) + to_wire
        encode_s/decode_s— lane-seconds in the wire codec
        transfer_s       — lane-seconds in device_put (transfer complete:
                           each lane blocks on readiness so a slot is
                           always fully resident when delivered — and so
                           this timer measures the wire, not the dispatch)
        transfer_busy_s  — UNION wall-clock during which >= 1 lane sat in
                           its transfer leg (transfer_mb_per_s's clock:
                           the effective link rate under concurrency)
        input_s          — host_s + encode_s + decode_s + transfer_s,
                           per-batch total (raw lane-seconds)
        steady_input_s   — UNION wall-clock with >= 1 lane anywhere in
                           its input leg (read+encode+transfer), windowed
                           to the consumer's steady state (first take ->
                           last take). input_overlap_fraction's
                           denominator: raw lane-seconds would count
                           concurrent lanes multiply and report a fully
                           ingest-bound multi-lane job as mostly
                           "hidden"; the union clock keeps the estimator
                           honest and comparable to the single-threaded
                           prefetch number
        batches_consumed — batches the consumer took
        consumer_wait_s  — consumer seconds blocked past the fill batch
        consumer_busy_s  — consumer seconds NOT blocked (its compute)
        wall_s           — consumer wall-clock from first to last take;
                           equals consumer_wait_s + consumer_busy_s
                           exactly (the stamps telescope)
        lanes / lanes_effective / codec — the engine config that RAN
    """
    import jax

    if depth < 1:
        raise ValueError("depth must be >= 1")
    if chunks < 1:
        raise ValueError("chunks must be >= 1")
    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    if codec not in WIRE_CODECS:
        raise ValueError(f"wire codec {codec!r} not in {WIRE_CODECS}")

    multiproc = jax.process_count() > 1
    assembly = sharding is not None and multiproc
    # A lane above depth could never hold a slot permit; the global-
    # assembly path owns its transfers (make_array_from_process_local_data
    # is not documented thread-safe, and its collectives must not race).
    n_lanes = 1 if assembly else max(1, min(lanes, depth))
    if stats is not None:
        for k in ("batches_staged", "batches_consumed", "bytes_staged",
                  "bytes_encoded"):
            stats.setdefault(k, 0)
        for k in ("host_s", "encode_s", "decode_s", "transfer_s",
                  "transfer_busy_s", "input_s", "steady_input_s",
                  "consumer_wait_s", "consumer_busy_s", "wall_s"):
            stats.setdefault(k, 0.0)
        stats["lanes"] = lanes
        stats["lanes_effective"] = n_lanes
        stats["codec"] = codec

    free = threading.Semaphore(depth)
    stop = threading.Event()
    # TWO locks, deliberately: `read_lock` serializes the sequenced
    # reader (next(it) can be a real disk read — holding the delivery
    # lock across it would block a finished lane's deposit and the
    # consumer's take behind host I/O, eroding exactly the overlap this
    # engine exists to create), while `lock`/`cond` guard the shared
    # stats, the wire-busy union clock, and the ordered delivery buffer.
    # Lock order is always read_lock -> cond, never the reverse.
    read_lock = threading.Lock()
    lock = threading.Lock()
    cond = threading.Condition(lock)
    ready: dict[int, Any] = {}  # seq -> staged tree
    err: list[BaseException] = []
    src = {"next_seq": 0, "total": None}
    wire = {"active": 0, "t0": 0.0}
    # Union clock over the WHOLE input leg (read+codec+transfer): the
    # overlap estimator's denominator. `acc` accumulates closed
    # intervals; an open interval (active > 0) is added on read.
    inp = {"active": 0, "t0": 0.0, "acc": 0.0}
    # Chaos stall directives (TPUJOB_CHAOS "stall:..."): deterministic
    # transfer-leg delays for fault-injection tests, optionally targeting
    # one lane (lane=L). Parsed once here; [] (the no-chaos path) costs
    # nothing per batch.
    from tf_operator_tpu.chaos import staging_stall_delay, staging_stalls_from_env

    stalls = staging_stalls_from_env()

    def put_tree(batch):
        if assembly:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(sharding, x),
                batch,
            )
        return jax.tree.map(
            lambda x: _put_chunks(x, sharding, chunks), batch
        )

    def _wire_enter():
        if stats is None:
            return
        with lock:
            if wire["active"] == 0:
                wire["t0"] = time.perf_counter()
            wire["active"] += 1

    def _wire_exit():
        if stats is None:
            return
        with lock:
            wire["active"] -= 1
            if wire["active"] == 0:
                stats["transfer_busy_s"] += time.perf_counter() - wire["t0"]

    def _input_enter():
        if stats is None:
            return
        with lock:
            if inp["active"] == 0:
                inp["t0"] = time.perf_counter()
            inp["active"] += 1

    def _input_exit():
        if stats is None:
            return
        with lock:
            inp["active"] -= 1
            if inp["active"] == 0:
                inp["acc"] += time.perf_counter() - inp["t0"]

    def _input_busy_now():
        # caller holds `lock`
        if inp["active"]:
            return inp["acc"] + (time.perf_counter() - inp["t0"])
        return inp["acc"]

    def worker(lane: int):
        try:
            while True:
                # A free ring slot gates the NEXT transfer — this is what
                # bounds read-ahead to `depth` device batches across ALL
                # lanes (a lane holds its permit while transferring).
                while not free.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                t0 = time.perf_counter()
                # The union input clock brackets the WHOLE leg (read +
                # codec + transfer); the finally closes the interval on
                # every early return and on the error path, so the
                # overlap denominator never counts a dead lane as busy.
                _input_enter()
                try:
                    # Tracer spans (--trace): each lane's host/wire and
                    # h2d legs land on their own track in the Chrome
                    # trace, so "did the transfer hide under compute" —
                    # and whether the lanes actually overlapped — is
                    # visible, not inferred. No-ops when tracing is off.
                    with telemetry.span("staging/host_next", lane=lane):
                        exhausted = False
                        with read_lock:
                            if src["total"] is not None or err:
                                free.release()
                                return
                            try:
                                batch = next(it)
                            except StopIteration:
                                src["total"] = src["next_seq"]
                                exhausted = True
                            else:
                                seq = src["next_seq"]
                                src["next_seq"] = seq + 1
                        if exhausted:
                            free.release()
                            with cond:
                                cond.notify_all()
                            return
                        if stop.is_set():
                            return
                        batch = to_wire(batch, wire_dtype)
                    enc_bytes, t_enc, t_dec = 0, 0.0, 0.0
                    if codec != "none":
                        # encode -> (the queue hop IS the notional
                        # single-host wire) -> decode, both host-side on
                        # this lane; the decoded arrays are what
                        # device_put ships.
                        te0 = time.perf_counter()
                        batch = encode_batch(batch, codec)
                        enc_bytes = encoded_nbytes(batch)
                        te1 = time.perf_counter()
                        batch = decode_batch(batch)
                        t_enc = te1 - te0
                        t_dec = time.perf_counter() - te1
                    if stats is not None:
                        with lock:
                            if "chunks_effective" not in stats:
                                # What the knob actually did for THIS job
                                # (leaf max): 1 on the global-assembly
                                # path and whenever every leaf is too
                                # small / indivisible — so a tuner
                                # reading transfer_mb_per_s knows whether
                                # chunking was live.
                                stats["chunks_effective"] = (
                                    1 if assembly else max(
                                        (effective_chunks(leaf, sharding,
                                                          chunks)
                                         for leaf in jax.tree.leaves(batch)),
                                        default=1))
                    # (attr computed only when tracing — span() evaluates
                    # its kwargs at the call site and a per-batch tree
                    # reduction is not "near-zero cost when disabled" —
                    # and BEFORE t1, so it charges to the host leg, never
                    # to transfer_s: the wire timer's accuracy is a
                    # pinned PR-2 contract)
                    _attrs = (
                        {"lane": lane,
                         "bytes": sum(x.nbytes
                                      for x in jax.tree.leaves(batch))}
                        if telemetry.get_tracer().enabled else {}
                    )
                    t1 = time.perf_counter()
                    with telemetry.span("staging/h2d_transfer", **_attrs):
                        _wire_enter()
                        try:
                            if stalls:
                                # Injected link stall: inside the wire
                                # window, charged to transfer_s AND
                                # transfer_busy_s like the real slow-wire
                                # failure it simulates.
                                delay = staging_stall_delay(seq, stalls,
                                                            lane=lane)
                                if delay > 0:
                                    time.sleep(delay)
                            dev = put_tree(batch)
                            # Block on transfer completion: the slot must
                            # be resident before the consumer can see it,
                            # and transfer_s must time the wire rather
                            # than the async dispatch. (_Chunks is an
                            # opaque leaf — unwrap to its arrays for the
                            # wait.)
                            jax.block_until_ready([
                                leaf.parts if isinstance(leaf, _Chunks)
                                else leaf
                                for leaf in jax.tree.leaves(dev)
                            ])
                        finally:
                            _wire_exit()
                    t2 = time.perf_counter()
                finally:
                    _input_exit()
                with cond:
                    if stats is not None:
                        stats["batches_staged"] += 1
                        stats["bytes_staged"] += sum(
                            x.nbytes for x in jax.tree.leaves(batch)
                        )
                        stats["bytes_encoded"] += enc_bytes
                        stats["host_s"] += t1 - t0 - t_enc - t_dec
                        stats["encode_s"] += t_enc
                        stats["decode_s"] += t_dec
                        stats["transfer_s"] += t2 - t1
                        stats["input_s"] += t2 - t0
                    # Ordered delivery: the slot waits HERE (keyed by its
                    # sequence number) until the consumer's cursor reaches
                    # it — lanes may finish out of order, consumers never
                    # see out of order.
                    ready[seq] = dev
                    cond.notify_all()
        except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
            with cond:
                err.append(e)
                cond.notify_all()

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True,
                         name=f"staging-{i}")
        for i in range(n_lanes)
    ]
    for t in threads:
        t.start()
    # Consumer stamps telescope: busy_i = t_get_i - t_take_{i-1} (caller
    # compute between takes), wait_i = t_item_i - t_get_i (blocked on the
    # ring), so wall_s = t_item_last - t_item_first == sum(busy) + sum(wait).
    t_prev_take = None
    inp_prev = 0.0
    expected = 0
    try:
        while True:
            t_get = time.perf_counter()
            with cond:
                while (expected not in ready and not err
                       and not (src["total"] is not None
                                and expected >= src["total"])):
                    cond.wait()
                if expected in ready:
                    # Delivered slots drain before an error/end surfaces —
                    # same semantics as the single-lane queue, where items
                    # queued ahead of the sentinel were always yielded.
                    item = ready.pop(expected)
                elif err:
                    raise err[0]
                else:
                    return
            expected += 1
            t_item = time.perf_counter()
            if stats is not None:
                with lock:
                    # Steady-state input = union-busy DELTA between takes:
                    # wall-clock with >= 1 lane in its input leg during
                    # the consumer's steady window. Per-batch lane-seconds
                    # here would count concurrent lanes multiply and read
                    # a fully ingest-bound multi-lane run as "hidden".
                    inp_now = _input_busy_now()
                    if t_prev_take is not None:
                        stats["consumer_busy_s"] += t_get - t_prev_take
                        stats["consumer_wait_s"] += t_item - t_get
                        stats["wall_s"] += t_item - t_prev_take
                        stats["steady_input_s"] += inp_now - inp_prev
                    inp_prev = inp_now
                    stats["batches_consumed"] += 1
            t_prev_take = t_item
            # Taking batch i frees a slot: batch i's arrays now belong to
            # the consumer/step, and a lane may overwrite the slot by
            # staging batch i+depth.
            free.release()
            # Chunk reassembly dispatches a PROGRAM, so it must happen here
            # on the consumer thread (see _assemble), async alongside the
            # step the caller dispatches next.
            yield _assemble(item, sharding)
    finally:
        stop.set()
        with cond:
            ready.clear()
            cond.notify_all()


# Auto-tuner probe grids: small powers of two around the proven operating
# points (PR 2 shipped chunks {2,4,8} as the manual sweep; lanes beyond 4
# never won a probe on either backend we can see — the reader lock and
# the link itself serialize first).
TUNE_LANES = (1, 2, 4)
TUNE_CHUNKS = (1, 2, 4)


def autotune_staging(
    sample_batch: dict,
    sharding=None,
    lanes_grid: tuple[int, ...] = TUNE_LANES,
    chunks_grid: tuple[int, ...] = TUNE_CHUNKS,
    reps: int = 3,
    depth: int | None = None,
    wire_dtype: str = "auto",
    codec: str = "none",
) -> dict:
    """Micro-probe {lanes x chunks} against the LIVE link and pick the
    best: each combination stages `reps` copies of `sample_batch` through
    a real ring with a zero-compute consumer and is scored by the ring's
    own wire clock (transfer_mb_per_s — bytes over wire-busy union), so
    the probe measures exactly the machinery the job will run, tunnel and
    sharding included. Pass the job's real `depth` so the probes run the
    geometry the job will (the ring caps lanes at depth). Ties break
    toward fewer lanes, then fewer chunks (less thread/dispatch overhead
    at equal rate).

    Returns {"lanes", "chunks", "mb_per_s", "table": [{lanes, chunks,
    requested, mb_per_s, delivered_mb_per_s}, ...], "reps", "probe_s"} —
    the table is recorded in the trainer's done-event accounting so a
    bench reader can audit WHY the tuner chose what it chose. Table rows
    are unique EFFECTIVE geometries (what a ring actually runs: lanes
    capped at depth, chunks degraded per-array, the multi-process
    assembly path forced to 1x1) and `requested` lists the grid combos
    that collapsed onto each row — degenerate combos are probed ONCE,
    not once per alias (on the assembly path the whole default grid is
    a single probe instead of 9 stagings of the full global batch), and
    the locked lanes/chunks always reproduce a configuration that was
    actually probed.

    The caller keeps `sample_batch` (probes only read it): peek one batch
    off the real host iterator, tune, then chain it back in front so the
    training trajectory is byte-identical to an untuned run.
    """
    import jax

    if not lanes_grid or not chunks_grid:
        raise ValueError("autotune_staging: empty probe grid")
    t_probe0 = time.perf_counter()
    assembly = sharding is not None and jax.process_count() > 1
    # Chunk feasibility is decided against the WIRE arrays (to_wire can
    # 4x a leaf's bytes across the MIN_CHUNK_BYTES threshold).
    wire_leaves = jax.tree.leaves(to_wire(sample_batch, wire_dtype))

    def _effective(lanes: int, chunks: int) -> tuple[int, int]:
        d = depth if depth is not None else max(2, lanes)
        if assembly:
            return 1, 1
        return (max(1, min(lanes, d)),
                max((effective_chunks(leaf, sharding, chunks)
                     for leaf in wire_leaves), default=1))

    table: list[dict] = []
    probed: dict[tuple[int, int], dict] = {}
    best = None
    for lanes in lanes_grid:
        for chunks in chunks_grid:
            eff = _effective(lanes, chunks)
            if eff in probed:
                # This combo degrades to an already-probed geometry —
                # measuring it again would stage reps more copies of the
                # batch to learn the same number.
                probed[eff]["requested"].append([lanes, chunks])
                continue
            stats: dict = {}
            it = stage_to_device(
                iter([sample_batch] * reps),
                depth=depth if depth is not None else max(2, lanes),
                sharding=sharding, chunks=chunks, wire_dtype=wire_dtype,
                stats=stats, lanes=lanes, codec=codec,
            )
            n = 0
            t0 = time.perf_counter()
            for dev in it:
                jax.block_until_ready(jax.tree.leaves(dev))
                n += 1
            dt = time.perf_counter() - t0
            rate = transfer_mb_per_s(stats) or 0.0
            row = {
                # the geometry this probe's ring ACTUALLY ran — the ring
                # reports it back (should equal `eff`; trust the ring)
                "lanes": stats.get("lanes_effective", eff[0]),
                "chunks": stats.get("chunks_effective", eff[1]),
                "requested": [[lanes, chunks]],
                "mb_per_s": round(rate, 2),
                "delivered_mb_per_s": (
                    round(stats.get("bytes_staged", 0) / 1e6 / dt, 2)
                    if dt > 0 else None),
            }
            table.append(row)
            probed[eff] = row
            if best is None or rate > best[0]:
                best = (rate, row["lanes"], row["chunks"])
    return {
        "lanes": best[1],
        "chunks": best[2],
        "mb_per_s": round(best[0], 2),
        "table": table,
        "reps": reps,
        "probe_s": round(time.perf_counter() - t_probe0, 3),
    }
