"""Asynchronous host->device input staging: uint8 wire + buffered ring.

The round-5 bench pinned the real-data ResNet point at 6.2% of synthetic
throughput and attributed the whole gap to ingest: the host pipeline
produced 2053 MB/s but serial f32 `device_put` moved ~52 MB/s against a
361 MB/s parity requirement. This module is the classic training-stack
answer, in two coordinated layers:

  1. **Wire format** (`to_wire`, `make_preprocess_fn`): ship images
     host->device as uint8 and cast/normalize on device *inside* the
     jitted step, where the cast fuses into the first conv's input read.
     4x fewer bytes on the wire drops the parity bar by 4x. Token batches
     (int32, already minimal) pass through the same API unchanged.

  2. **Staging ring** (`stage_to_device`): K device-batch slots fed by a
     background transfer thread, so the transfer of batch N+1 overlaps the
     compute of batch N. The ring bounds in-flight device memory to K
     staged batches (+1 being consumed): a slot frees when the consumer
     takes the next batch, and XLA's allocator recycles the freed arrays'
     pages for the next transfer. Transfers can be *chunked* — split along
     the batch dim into C concurrent `device_put` calls reassembled
     on-device — which raises the effective rate on links where a single
     serial put can't fill the pipe (the tunnel, PCIe with small copies).

Accounting is explicit (the bench reports numbers, not assertions):
`transfer_mb_per_s` from the producer's own put timers, and
`input_overlap_fraction` — the share of steady-state input seconds that
hid under compute — from stamps that telescope exactly to the consumer's
wall-clock (wall_s == consumer_wait_s + consumer_busy_s by construction,
which tests verify against a synthetic slow producer).

Normalization math is defined ONCE here (multiply by a f32-rounded
reciprocal) and used by both the host-side f32 wire path and the
on-device preprocess hook: `--wire-dtype f32` and `--wire-dtype uint8`
trajectories agree to FMA-contraction rounding (XLA fuses the mul-sub
where numpy rounds twice; the CPU parity test pins the divergence at
rtol 1e-3 over 6 optimizer steps, 1e-4 on the first). Staged vs prefetch
ingest of the SAME wire — identical device ops — IS bit-identical, and
tested as exact equality.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from tf_operator_tpu import telemetry
from tf_operator_tpu.data.prefetch import overlap_efficiency

# f32-rounded reciprocal, multiplied (not divided) on BOTH host and device:
# the same IEEE single-precision ops in the same order keeps the uint8-wire
# and f32-wire trajectories together up to XLA's FMA contraction of the
# mul-sub (the one rounding difference the parity test bounds).
U8_SCALE = np.float32(1.0) / np.float32(127.5)
U8_SHIFT = np.float32(1.0)

WIRE_DTYPES = ("auto", "uint8", "f32")

# Batch keys carrying images (the arrays the uint8 wire + on-device
# normalize applies to). uint8 elsewhere — labels under 256 classes,
# 0/1 masks — is DATA, not pixels: normalizing it would corrupt it
# (float class indices crash take_along_axis; a {-1, -0.99} mask
# silently wrecks the loss). Every model entry in models/train.py uses
# "x" for its image tensor; extend here if that contract grows.
IMAGE_KEYS = ("x",)


class _Stop:
    pass


def normalize_uint8(x):
    """uint8 pixels -> f32 in [-1, 1], on whichever backend `x` lives.

    jnp arrays normalize on device (fused into the consuming op); numpy
    arrays normalize on host (the f32 wire path) with the identical
    constant and op order.
    """
    if isinstance(x, np.ndarray):
        return x.astype(np.float32) * U8_SCALE - U8_SHIFT
    import jax.numpy as jnp

    return x.astype(jnp.float32) * U8_SCALE - U8_SHIFT


def to_wire(batch: dict, wire_dtype: str = "auto",
            image_keys: tuple[str, ...] = IMAGE_KEYS) -> dict:
    """Host-side wire-format conversion of one dict batch. Only
    `image_keys` entries are ever converted — uint8 labels/masks are data
    and pass through under every wire dtype.

    auto  — ship every array as stored (uint8 stays uint8: the cheap wire).
    uint8 — contract check: image keys must already be uint8 (storing f32
            and quantizing here would silently lose data); everything
            else (labels, tokens, masks) passes through.
    f32   — normalize uint8 image keys to f32 ON HOST (the 4x-wider wire,
            kept as the parity reference for the on-device cast).
    """
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(f"wire_dtype {wire_dtype!r} not in {WIRE_DTYPES}")
    if wire_dtype == "auto":
        return batch
    out = {}
    for k, v in batch.items():
        if k not in image_keys:
            out[k] = v
        elif wire_dtype == "f32" and v.dtype == np.uint8:
            out[k] = normalize_uint8(v)
        elif wire_dtype == "uint8" and np.issubdtype(v.dtype, np.floating):
            raise ValueError(
                f"--wire-dtype uint8 needs uint8-stored images, but key "
                f"{k!r} is {v.dtype} — re-shard the dataset as uint8 or "
                f"use --wire-dtype auto/f32"
            )
        else:
            out[k] = v
    return out


def make_preprocess_fn(
    image_keys: tuple[str, ...] = IMAGE_KEYS,
) -> Callable[[dict], dict]:
    """On-device batch preprocessor for the train step's preprocess hook:
    normalizes uint8 IMAGE entries (the uint8 wire) and passes everything
    else (tokens, labels, masks, already-f32 images) through — uint8
    outside `image_keys` is data, never pixels. Traced into the jitted
    step, so the cast/normalize fuses with the first consumer of the
    batch and never materializes a second f32 copy in the host->device
    path."""
    import jax.numpy as jnp

    def preprocess(batch):
        return {
            k: normalize_uint8(v)
            if k in image_keys and v.dtype == jnp.uint8 else v
            for k, v in batch.items()
        }

    return preprocess


class _Chunks:
    """Opaque holder for one array staged as C chunk transfers, awaiting
    consumer-side reassembly. Deliberately NOT a pytree container, so
    jax.tree.map treats it as a leaf."""

    __slots__ = ("parts",)

    def __init__(self, parts):
        self.parts = parts


# Arrays under this size transfer in ONE put regardless of the chunks
# knob: a label/mask vector is a few KB — splitting it buys nothing and
# multiplies per-put dispatch overhead.
MIN_CHUNK_BYTES = 1 << 20


def _dim0_shards(sharding, shape) -> int:
    """How many pieces the sharding splits dim 0 into (1 when unsharded or
    unanswerable) — each chunk's leading dim must stay divisible by this."""
    if sharding is None or not shape:
        return 1
    try:
        return shape[0] // sharding.shard_shape(tuple(shape))[0]
    except Exception:  # noqa: BLE001 — exotic shardings: just don't chunk
        return 0


def effective_chunks(x, sharding=None, chunks: int = 1) -> int:
    """Largest feasible chunk count <= requested for THIS array: chunking
    is a transfer-rate knob, not semantics, so infeasible configs degrade
    instead of erroring — the requested count may not divide the leading
    dim, and each chunk must itself remain shardable over the mesh's data
    axes (a 24-row batch on dp=8 works unchunked but no 4-way split of it
    leaves rows divisible by 8)."""
    if (chunks <= 1 or x.ndim == 0 or x.shape[0] < chunks
            or x.nbytes < MIN_CHUNK_BYTES):
        return 1
    nsh = _dim0_shards(sharding, x.shape)
    if nsh == 0:
        return 1
    for c in range(chunks, 1, -1):
        if x.shape[0] % c == 0 and (x.shape[0] // c) % nsh == 0:
            return c
    return 1


def _put_chunks(x, sharding=None, chunks: int = 1, strict: bool = False):
    """TRANSFERS ONLY — safe from a background thread.

    device_put is async: issuing C smaller puts along the leading dim lets
    the transfers stream back-to-back instead of serializing behind one
    large copy, raising the effective rate on links a single put can't
    fill. Returns a _Chunks awaiting reassembly, or a plain array when the
    chunk count resolves to 1.

    strict=False (the staging ring): chunking degrades per-array via
    effective_chunks — a perf knob must not crash the transfer thread.
    strict=True (the explicit chunked_device_put API, benchmarks/tests):
    chunk exactly as asked, raising a clear error on an infeasible split.
    """
    import jax

    def put(v):
        return jax.device_put(v, sharding) if sharding is not None \
            else jax.device_put(v)

    if strict and chunks > 1:
        if x.ndim == 0 or x.shape[0] < chunks:
            chunks = 1  # nothing to split — documented fallback
        elif x.shape[0] % chunks:
            raise ValueError(
                f"chunks {chunks} does not divide leading dim {x.shape[0]}"
            )
        else:
            nsh = _dim0_shards(sharding, x.shape)
            if nsh == 0 or (nsh > 1 and (x.shape[0] // chunks) % nsh):
                raise ValueError(
                    f"chunks {chunks} leaves {x.shape[0] // chunks}-row "
                    f"chunks the sharding cannot split over its {nsh} "
                    f"dim-0 shards"
                )
    else:
        chunks = effective_chunks(x, sharding, chunks)
    if chunks <= 1:
        return put(x)
    step = x.shape[0] // chunks
    return _Chunks([put(x[i * step:(i + 1) * step]) for i in range(chunks)])


def _assemble(tree, sharding=None):
    """Consumer-side chunk reassembly: jnp.concatenate COMPILES A PROGRAM,
    and on a multi-device mesh concurrently dispatched programs can enqueue
    their collectives in different per-device orders and deadlock — so
    reassembly must run on the thread that also dispatches the train step
    (one dispatch order), never on the transfer thread. The transfer thread
    only ever calls device_put (no program), which the prefetcher already
    proved safe."""
    import jax
    import jax.numpy as jnp

    def join(leaf):
        if not isinstance(leaf, _Chunks):
            return leaf
        out = jnp.concatenate(leaf.parts, axis=0)
        # Re-pin the step's expected batch sharding: the concat output's
        # layout is XLA's choice, and jit(in_shardings=...) rejects
        # mismatched committed arrays rather than resharding them.
        return jax.device_put(out, sharding) if sharding is not None else out

    return jax.tree.map(join, tree)


def chunked_device_put(x, sharding=None, chunks: int = 1):
    """Single-thread convenience: chunked transfer + immediate reassembly
    (tools/exp_transfer.py and tests) — STRICT: chunks exactly as asked or
    raises, so a benchmark never silently measures the unchunked path. The
    staging ring itself degrades gracefully instead and keeps the two
    phases on their proper threads — see _put_chunks/_assemble."""
    return _assemble(_put_chunks(x, sharding, chunks, strict=True), sharding)


def transfer_mb_per_s(stats: dict) -> float | None:
    """Effective host->device transfer rate from the producer thread's own
    put timers (wire bytes / seconds actually spent in device_put)."""
    s = stats.get("transfer_s", 0.0)
    b = stats.get("bytes_staged", 0)
    if s <= 0 or b <= 0:
        return None
    return b / 1e6 / s


def input_overlap_fraction(stats: dict) -> float | None:
    """Share of the steady-state input path (host production + wire cast +
    transfer of the consumed batches past pipeline fill) that hid under
    compute. Same estimator as prefetch.overlap_efficiency — the staging
    ring populates the identical keys, so the two pipelines' numbers are
    directly comparable."""
    return overlap_efficiency(stats)


def stage_to_device(
    it: Iterator[Any],
    depth: int = 2,
    sharding=None,
    chunks: int = 1,
    wire_dtype: str = "auto",
    stats: dict | None = None,
) -> Iterator[Any]:
    """Wrap a host-batch iterator; yields batches staged on device through
    a ring of `depth` slots fed by a background transfer thread.

    depth      — ring size K: how many batches may be device-resident ahead
                 of the consumer (2 = classic double buffering). In-flight
                 device memory is bounded by K staged (+1 being consumed).
    sharding   — optional jax.sharding.Sharding for the put (multi-process
                 jobs assemble the global batch from local slices, like
                 prefetch_to_device).
    chunks     — concurrent device_put transfers per array, degraded
                 per-array to the largest feasible count (effective_chunks:
                 size threshold, leading-dim and shard divisibility) and
                 NOT applied on the multi-process global-assembly path
                 (sharding given AND process_count > 1 — that path owns
                 its transfers); stats records the applied value as
                 chunks_effective so reported numbers never claim chunking
                 that didn't happen.
    wire_dtype — host-side wire conversion (see to_wire). On-device
                 normalization of the uint8 wire is the train step's
                 preprocess hook, not the stager's job.
    stats      — optional dict updated IN PLACE while the iterator is live:
        batches_staged   — batches the producer finished transferring
        bytes_staged     — wire bytes moved host->device
        host_s           — producer seconds in next(it) + to_wire
        transfer_s       — producer seconds in device_put (transfer
                           complete: the producer blocks on readiness so
                           a slot is always fully resident when yielded —
                           and so this timer measures the wire, not the
                           dispatch)
        input_s          — host_s + transfer_s, per-batch total (raw)
        steady_input_s   — input seconds of just the CONSUMED steady-state
                           batches (input_overlap_fraction's denominator)
        batches_consumed — batches the consumer took
        consumer_wait_s  — consumer seconds blocked past the fill batch
        consumer_busy_s  — consumer seconds NOT blocked (its compute)
        wall_s           — consumer wall-clock from first to last take;
                           equals consumer_wait_s + consumer_busy_s
                           exactly (the stamps telescope)
    """
    import jax

    if depth < 1:
        raise ValueError("depth must be >= 1")
    if chunks < 1:
        raise ValueError("chunks must be >= 1")
    if stats is not None:
        for k in ("batches_staged", "batches_consumed"):
            stats.setdefault(k, 0)
        stats.setdefault("bytes_staged", 0)
        for k in ("host_s", "transfer_s", "input_s", "steady_input_s",
                  "consumer_wait_s", "consumer_busy_s", "wall_s"):
            stats.setdefault(k, 0.0)

    multiproc = jax.process_count() > 1
    pending_times: collections.deque = collections.deque()
    free = threading.Semaphore(depth)
    q: queue.Queue = queue.Queue()
    err: list[BaseException] = []
    stop = threading.Event()
    # Chaos stall directives (TPUJOB_CHAOS "stall:..."): deterministic
    # transfer-leg delays for fault-injection tests. Parsed once here; []
    # (the no-chaos path) costs nothing per batch.
    from tf_operator_tpu.chaos import staging_stall_delay, staging_stalls_from_env

    stalls = staging_stalls_from_env()

    def put_tree(batch):
        if sharding is not None and multiproc:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(sharding, x),
                batch,
            )
        return jax.tree.map(
            lambda x: _put_chunks(x, sharding, chunks), batch
        )

    def worker():
        staged_idx = 0
        try:
            while True:
                # A free ring slot gates the NEXT transfer — this is what
                # bounds read-ahead to `depth` device batches.
                while not free.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                t0 = time.perf_counter()
                # Tracer spans (--trace): the transfer thread's host/wire
                # and h2d legs land on their own track in the Chrome
                # trace, so "did the transfer hide under compute" is
                # visible, not inferred. No-ops when tracing is off.
                with telemetry.span("staging/host_next"):
                    try:
                        batch = next(it)
                    except StopIteration:
                        return
                    if stop.is_set():
                        return
                    batch = to_wire(batch, wire_dtype)
                if stats is not None and "chunks_effective" not in stats:
                    # What the knob actually did for THIS job (leaf max):
                    # 1 on the global-assembly path (the same condition
                    # put_tree branches on) and whenever every leaf is
                    # too small / indivisible — so a tuner reading
                    # transfer_mb_per_s knows whether chunking was live.
                    assembly = sharding is not None and multiproc
                    stats["chunks_effective"] = 1 if assembly else max(
                        (effective_chunks(leaf, sharding, chunks)
                         for leaf in jax.tree.leaves(batch)), default=1)
                # (attr computed only when tracing — span() evaluates its
                # kwargs at the call site and a per-batch tree reduction
                # is not "near-zero cost when disabled" — and BEFORE t1,
                # so it charges to the host leg, never to transfer_s: the
                # wire timer's accuracy is a pinned PR-2 contract)
                _attrs = (
                    {"bytes": sum(x.nbytes for x in jax.tree.leaves(batch))}
                    if telemetry.get_tracer().enabled else {}
                )
                t1 = time.perf_counter()
                with telemetry.span("staging/h2d_transfer", **_attrs):
                    if stalls:
                        # Injected link stall: charged to transfer_s like
                        # the real slow-wire failure it simulates.
                        delay = staging_stall_delay(staged_idx, stalls)
                        if delay > 0:
                            time.sleep(delay)
                    staged_idx += 1
                    dev = put_tree(batch)
                    # Block on transfer completion: the slot must be
                    # resident before the consumer can see it, and
                    # transfer_s must time the wire rather than the async
                    # dispatch. (_Chunks is an opaque leaf — unwrap to its
                    # arrays for the wait.)
                    jax.block_until_ready([
                        leaf.parts if isinstance(leaf, _Chunks) else leaf
                        for leaf in jax.tree.leaves(dev)
                    ])
                t2 = time.perf_counter()
                if stats is not None:
                    # One producer thread: plain += is safe. Per-batch time
                    # queues BEFORE the batch so the consumer's popleft
                    # pairs with the batch it just took.
                    stats["batches_staged"] += 1
                    stats["bytes_staged"] += sum(
                        x.nbytes for x in jax.tree.leaves(batch)
                    )
                    stats["host_s"] += t1 - t0
                    stats["transfer_s"] += t2 - t1
                    stats["input_s"] += t2 - t0
                    pending_times.append(t2 - t0)
                q.put(dev)
        except BaseException as e:  # noqa: BLE001 — surfaced to the consumer
            err.append(e)
        finally:
            q.put(_Stop)  # unbounded queue: delivery never blocks

    t = threading.Thread(target=worker, daemon=True, name="staging")
    t.start()
    # Consumer stamps telescope: busy_i = t_get_i - t_take_{i-1} (caller
    # compute between takes), wait_i = t_item_i - t_get_i (blocked on the
    # ring), so wall_s = t_item_last - t_item_first == sum(busy) + sum(wait).
    t_prev_take = None
    try:
        while True:
            t_get = time.perf_counter()
            item = q.get()
            t_item = time.perf_counter()
            if item is _Stop:
                if err:
                    raise err[0]
                return
            if stats is not None:
                produced_s = pending_times.popleft() if pending_times else 0.0
                if t_prev_take is not None:
                    stats["consumer_busy_s"] += t_get - t_prev_take
                    stats["consumer_wait_s"] += t_item - t_get
                    stats["wall_s"] += t_item - t_prev_take
                    stats["steady_input_s"] += produced_s
                stats["batches_consumed"] += 1
            t_prev_take = t_item
            # Taking batch i frees a slot: batch i's arrays now belong to
            # the consumer/step, and the producer may overwrite the slot by
            # staging batch i+depth.
            free.release()
            # Chunk reassembly dispatches a PROGRAM, so it must happen here
            # on the consumer thread (see _assemble), async alongside the
            # step the caller dispatches next.
            yield _assemble(item, sharding)
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
