"""Sharded on-disk datasets: the input pipeline for real (non-synthetic) data.

The reference delegated data to user containers and offered one operator-side
hook: the fork's `((index))` volumeMount-subPath substitution so each replica
mounts its own data shard (SURVEY.md §0 fork delta 3, pod.go:50-85). This
module is the data-layer half of that contract, TPU-native:

  - shards are plain .npy files per key (`{key}_{shard:05d}.npy`), loaded
    with mmap so a pod touches only the pages its batches read;
  - `shard_from_env()` picks this replica's shard list from the same env the
    operator injects for the cluster spec (JAX process id/count), giving
    disjoint coverage with no coordination;
  - batches are numpy dicts ready for `prefetch.prefetch_to_device`.

Static shapes by construction: every shard stores fixed-shape samples, and
the batch iterator drops the remainder so XLA compiles the train step once.
"""

from __future__ import annotations

import json
import os
from typing import Iterator

import numpy as np

MANIFEST = "dataset.json"


def write_array_shards(
    out_dir: str, arrays: dict[str, np.ndarray], num_shards: int
) -> list[str]:
    """Split `arrays` (all with equal leading dim) into `num_shards` shard
    files per key plus a manifest; returns the shard file paths."""
    n = {a.shape[0] for a in arrays.values()}
    if len(n) != 1:
        raise ValueError(f"arrays disagree on sample count: { {k: v.shape for k, v in arrays.items()} }")
    total = n.pop()
    if num_shards < 1 or num_shards > total:
        raise ValueError(f"num_shards {num_shards} not in [1, {total}]")
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    bounds = np.linspace(0, total, num_shards + 1).astype(int)
    for key, arr in arrays.items():
        for s in range(num_shards):
            path = os.path.join(out_dir, f"{key}_{s:05d}.npy")
            np.save(path, arr[bounds[s]:bounds[s + 1]])
            paths.append(path)
    with open(os.path.join(out_dir, MANIFEST), "w") as f:
        json.dump(
            {
                "num_shards": num_shards,
                "total_samples": int(total),
                "keys": {
                    k: {"dtype": str(a.dtype), "shape": list(a.shape[1:])}
                    for k, a in arrays.items()
                },
            },
            f,
        )
    return paths


def shard_from_env() -> tuple[int, int]:
    """(shard_index, num_readers) from the operator-injected process env;
    (0, 1) for standalone runs."""
    pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    nprocs = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    return pid, max(nprocs, 1)


class ShardedDataset:
    """mmap-backed view over this reader's shards.

    reader_index/num_readers select a disjoint subset of shards round-robin
    (shard s belongs to reader s % num_readers), so N replicas jointly cover
    the dataset exactly once per epoch.
    """

    def __init__(self, data_dir: str, reader_index: int = 0, num_readers: int = 1):
        if not os.path.isdir(data_dir):
            raise FileNotFoundError(data_dir)
        if not 0 <= reader_index < num_readers:
            raise ValueError(f"reader {reader_index} not in [0, {num_readers})")
        with open(os.path.join(data_dir, MANIFEST)) as f:
            self.manifest = json.load(f)
        self.data_dir = data_dir
        self.num_shards = int(self.manifest["num_shards"])
        self.keys = sorted(self.manifest["keys"])
        my_shards = [
            s for s in range(self.num_shards) if s % num_readers == reader_index
        ]
        if not my_shards:
            raise ValueError(
                f"reader {reader_index}/{num_readers} has no shards "
                f"(dataset has {self.num_shards})"
            )
        self._arrays: dict[str, np.ndarray] = {}
        for key in self.keys:
            parts = [
                np.load(
                    os.path.join(self.data_dir, f"{key}_{s:05d}.npy"),
                    mmap_mode="r",
                )
                for s in my_shards
            ]
            # Concatenation of mmaps materializes; keep the shard list and a
            # flat index instead so reads stay lazy.
            self._arrays[key] = parts  # type: ignore[assignment]
        # Per-shard lengths must match across keys, not just totals: _gather
        # builds shard offsets from the first key only, so misaligned
        # hand-written shards would silently pair rows across keys wrong.
        first_lens = [p.shape[0] for p in self._arrays[self.keys[0]]]
        for k in self.keys[1:]:
            lens_k = [p.shape[0] for p in self._arrays[k]]
            if lens_k != first_lens:
                raise ValueError(
                    f"per-shard lengths differ between keys: "
                    f"{self.keys[0]}={first_lens} vs {k}={lens_k}"
                )
        self.num_samples = sum(first_lens)
        self._offsets = np.cumsum([0] + first_lens)

    def _gather(self, key: str, idx: np.ndarray) -> np.ndarray:
        """Gather rows by flat local index across the shard list."""
        parts = self._arrays[key]
        out = None
        shard_of = np.searchsorted(self._offsets, idx, side="right") - 1
        for s, part in enumerate(parts):
            mask = shard_of == s
            if not mask.any():
                continue
            rows = np.asarray(part[idx[mask] - self._offsets[s]])
            if out is None:
                out = np.empty((len(idx),) + rows.shape[1:], rows.dtype)
            out[mask] = rows
        return out

    def batches(
        self,
        batch_size: int,
        seed: int | None = 0,
        loop: bool = True,
        start_batch: int = 0,
    ) -> Iterator[dict[str, np.ndarray]]:
        """Dict batches of `batch_size` (remainder dropped — static shapes).
        seed=None iterates in order; otherwise shuffles per epoch.
        start_batch fast-forwards the stream (deterministic position, so a
        resumed trainer continues the exact batch sequence rather than
        replaying epoch 0)."""
        if batch_size > self.num_samples:
            raise ValueError(
                f"batch {batch_size} > local samples {self.num_samples}"
            )
        per_epoch = self.num_samples // batch_size
        epoch, skip = divmod(max(start_batch, 0), per_epoch)
        while True:
            idx = np.arange(self.num_samples)
            if seed is not None:
                np.random.default_rng(seed + epoch).shuffle(idx)
            for b in range(skip, per_epoch):
                take = idx[b * batch_size:(b + 1) * batch_size]
                yield {k: self._gather(k, take) for k in self.keys}
            skip = 0
            if not loop:
                return
            epoch += 1
