from tf_operator_tpu.data.dataset import (  # noqa: F401
    ShardedDataset,
    shard_from_env,
    write_array_shards,
)
from tf_operator_tpu.data.prefetch import prefetch_to_device  # noqa: F401
