from tf_operator_tpu.data.dataset import (  # noqa: F401
    ShardedDataset,
    shard_from_env,
    write_array_shards,
)
from tf_operator_tpu.data.prefetch import prefetch_to_device  # noqa: F401
from tf_operator_tpu.data.staging import (  # noqa: F401
    chunked_device_put,
    input_overlap_fraction,
    make_preprocess_fn,
    normalize_uint8,
    stage_to_device,
    to_wire,
    transfer_mb_per_s,
)
