"""Job condition state machine.

Capability parity with pkg/controller.v1/tensorflow/status.go:61-304:

  - replica-phase counts -> job conditions Created/Running/Restarting/
    Succeeded/Failed
  - success semantics: when a Chief/Master exists the job succeeds iff the
    chief completes; otherwise worker-0 acts as chief (worker0_completed), or
    — under SuccessPolicy AllWorkers — every worker must finish
  - failed>0 resolves to Restarting when the controller just restarted a
    replica (restart flag), else Failed + completion time
  - condition exclusivity: Running and Restarting displace each other;
    terminal conditions demote Running to status=False
    (setCondition/filterOutCondition, status.go:256-304)
  - Prometheus counters on success/failure/restart transitions
"""

from __future__ import annotations

import time

from tf_operator_tpu.api.types import (
    JobCondition,
    JobConditionType,
    JobStatus,
    ReplicaStatus,
    ReplicaType,
    TrainJob,
)
from tf_operator_tpu.core.cluster import Pod, PodPhase
from tf_operator_tpu.status import metrics

# Condition reasons (stable API surface; tests and events assert on these).
REASON_CREATED = "TrainJobCreated"
REASON_RUNNING = "TrainJobRunning"
REASON_RESTARTING = "TrainJobRestarting"
REASON_SUCCEEDED = "TrainJobSucceeded"
REASON_FAILED = "TrainJobFailed"
REASON_INVALID_SPEC = "TrainJobFailedValidation"
REASON_BACKOFF_EXCEEDED = "BackoffLimitExceeded"
REASON_DEADLINE_EXCEEDED = "DeadlineExceeded"
REASON_SUSPENDED = "TrainJobSuspended"
# Gang-coherent recovery (round 10): a slice-wide roll gets its own
# Restarting reason so dashboards/tests can tell it from a single-pod
# replacement; the stale-heartbeat warning and stuck-Pending warning are
# event reasons with the same stability contract.
REASON_GANG_RESTART = "GangRestart"
REASON_HEARTBEAT_STALE = "HeartbeatStale"
REASON_STUCK_PENDING = "StuckPending"
# Fleet scheduler (sched/): Queued = admitted but waiting for capacity or
# namespace quota; Preempted = gracefully evicted for a higher-priority
# job (a planned disruption — never Failed, never counted against
# backoffLimit).
REASON_QUEUED = "WaitingForCapacity"
REASON_QUOTA = "QuotaExhausted"
REASON_PREEMPTED = "PreemptedByHigherPriority"
# Elastic recovery (recovery.elastic): GangReshaped marks a gang
# re-admitted below its spec size because full capacity is gone;
# GangRestored marks the scale back to full size once capacity frees.
# Restart tallies and backoffLimit are NEVER touched by either.
REASON_GANG_RESHAPED = "GangReshaped"
REASON_GANG_RESTORED = "GangRestored"


def record_gang_restart(job: TrainJob, message: str, now: float) -> bool:
    """Set the Restarting condition for a gang-coherent restart (reason
    GangRestart) and count the jobs_restarted transition — the
    gang-recovery analogue of update_status_single's restart branch.
    Returns True when the condition changed."""
    changed = set_condition(
        job.status, JobConditionType.RESTARTING, REASON_GANG_RESTART,
        message, now,
    )
    if changed:
        metrics.jobs_restarted.labels(namespace=job.namespace).inc()
    return changed


def _find(status: JobStatus, ctype: JobConditionType) -> JobCondition | None:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def set_condition(status: JobStatus, ctype: JobConditionType, reason: str, message: str,
                  now: float | None = None) -> bool:
    """Append/replace a condition; returns True when status changed.
    Mirrors setCondition + filterOutCondition (status.go:256-304)."""
    now = time.time() if now is None else now
    cur = _find(status, ctype)
    if cur is not None and cur.status and cur.reason == reason and cur.message == message:
        return False

    new_cond = JobCondition(
        type=ctype, status=True, reason=reason, message=message,
        last_update_time=now, last_transition_time=now,
    )
    keep: list[JobCondition] = []
    for c in status.conditions:
        if c.type == ctype:
            continue
        # Running, Restarting, Suspended, Queued, and Preempted are
        # mutually exclusive views of the job's activity state.
        _ACTIVE = (JobConditionType.RUNNING, JobConditionType.RESTARTING,
                   JobConditionType.SUSPENDED, JobConditionType.QUEUED,
                   JobConditionType.PREEMPTED)
        if ctype in _ACTIVE and c.type in _ACTIVE:
            continue
        # A terminal condition demotes Running to status=False.
        if (
            ctype in (JobConditionType.SUCCEEDED, JobConditionType.FAILED)
            and c.type == JobConditionType.RUNNING
            and c.status
        ):
            c.status = False
            c.last_transition_time = now
        keep.append(c)
    keep.append(new_cond)
    status.conditions = keep
    return True


def lower_condition(status: JobStatus, ctype: JobConditionType, reason: str,
                    message: str, now: float | None = None) -> bool:
    """Set an existing condition's status to False (the 'no longer true
    but keep the record' shape k8s uses for informational conditions —
    here: GangReshaped once the gang is back at full size). No-op when
    the condition is absent or already False with this reason."""
    now = time.time() if now is None else now
    cur = _find(status, ctype)
    if cur is None or (not cur.status and cur.reason == reason):
        return False
    cur.status = False
    cur.reason = reason
    cur.message = message
    cur.last_update_time = now
    cur.last_transition_time = now
    return True


def initialize_replica_statuses(status: JobStatus, rtype: ReplicaType) -> None:
    status.replica_statuses[rtype] = ReplicaStatus()


def update_replica_status_counts(
    status: JobStatus, rtype: ReplicaType, pods: list[Pod]
) -> None:
    """Pod phases -> active/succeeded/failed counts (status.go:202)."""
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    rs.active = sum(1 for p in pods if p.status.phase == PodPhase.RUNNING)
    rs.succeeded = sum(1 for p in pods if p.status.phase == PodPhase.SUCCEEDED)
    rs.failed = sum(1 for p in pods if p.status.phase == PodPhase.FAILED)


def has_chief_or_master(job: TrainJob) -> bool:
    return (
        ReplicaType.CHIEF in job.spec.replica_specs
        or ReplicaType.MASTER in job.spec.replica_specs
    )


def update_status_single(
    job: TrainJob,
    rtype: ReplicaType,
    replicas: int,
    restart: bool,
    worker0_completed: bool,
    now: float | None = None,
) -> None:
    """Fold one replica type's counts into job conditions
    (updateStatusSingle, status.go:61-171)."""
    now = time.time() if now is None else now
    status = job.status
    if status.start_time is None:
        status.start_time = now

    rs = status.replica_statuses.get(rtype, ReplicaStatus())
    expected = replicas - rs.succeeded
    running, failed = rs.active, rs.failed
    name = f"{job.namespace}/{job.name}"

    if has_chief_or_master(job):
        if rtype in (ReplicaType.CHIEF, ReplicaType.MASTER):
            if running > 0:
                set_condition(
                    status, JobConditionType.RUNNING, REASON_RUNNING,
                    f"TrainJob {name} is running.", now,
                )
            if expected == 0:
                if set_condition(
                    status, JobConditionType.SUCCEEDED, REASON_SUCCEEDED,
                    f"TrainJob {name} successfully completed.", now,
                ):
                    metrics.jobs_successful.labels(namespace=job.namespace).inc()
                if status.completion_time is None:
                    status.completion_time = now
    else:
        if rtype is ReplicaType.WORKER:
            all_workers_done = expected == 0
            default_policy = job.spec.success_policy.policy != "AllWorkers"
            if all_workers_done or (worker0_completed and default_policy):
                if set_condition(
                    status, JobConditionType.SUCCEEDED, REASON_SUCCEEDED,
                    f"TrainJob {name} successfully completed.", now,
                ):
                    metrics.jobs_successful.labels(namespace=job.namespace).inc()
                if status.completion_time is None:
                    status.completion_time = now
            elif running > 0:
                set_condition(
                    status, JobConditionType.RUNNING, REASON_RUNNING,
                    f"TrainJob {name} is running.", now,
                )

    if failed > 0:
        if restart:
            if set_condition(
                status, JobConditionType.RESTARTING, REASON_RESTARTING,
                f"TrainJob {name} is restarting because {failed} {rtype} "
                "replica(s) failed.", now,
            ):
                metrics.jobs_restarted.labels(namespace=job.namespace).inc()
        else:
            if set_condition(
                status, JobConditionType.FAILED, REASON_FAILED,
                f"TrainJob {name} has failed because {failed} {rtype} "
                "replica(s) failed.", now,
            ):
                metrics.jobs_failed.labels(namespace=job.namespace).inc()
            if status.completion_time is None:
                status.completion_time = now
