"""Job status: condition state machine + metrics."""
