"""Operator metrics: Prometheus-compatible counters/gauges.

Capability parity with the reference's prometheus client usage:
tpujob_operator_jobs_{created,deleted,successful,failed,restarted}_total
(ref job.go:30-34, controller.go:68-72, status.go:46-58) and the leader gauge
(server.go:62-66). Exposed in Prometheus text format by cli.metrics_server.
"""

from __future__ import annotations

import threading


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    def value(self) -> float:
        with self._lock:
            return self._v


class Gauge(Counter):
    def set(self, v: float) -> None:
        with self._lock:
            self._v = v


class Histogram:
    """Cumulative-bucket histogram, Prometheus semantics: each `le` bucket
    counts observations <= its bound, plus +Inf / _sum / _count series.
    The reference logs per-reconcile sync latency (controller.go:289-291);
    this surfaces the same signal as a scrapeable distribution."""

    # Reconcile passes are ms-scale in-memory and seconds-scale against a
    # real apiserver; buckets span both.
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose_lines(self) -> list[str]:
        with self._lock:
            lines = []
            if self.help:
                lines.append(f"# HELP {self.name} {self.help}")
            lines.append(f"# TYPE {self.name} histogram")
            cum = 0
            for b, c in zip(self.buckets, self._counts):
                cum += c
                lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
            cum += self._counts[-1]
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {cum}")
            return lines


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Counter(name, help_text)
            return self._metrics[name]

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Gauge(name, help_text)
            m = self._metrics[name]
            assert isinstance(m, Gauge)
            return m

    def histogram(self, name: str, help_text: str = "") -> Histogram:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Histogram(name, help_text)
            m = self._metrics[name]
            assert isinstance(m, Histogram)
            return m

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            lines = []
            for m in self._metrics.values():
                if isinstance(m, Histogram):
                    lines.extend(m.expose_lines())
                    continue
                kind = "gauge" if isinstance(m, Gauge) else "counter"
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {kind}")
                lines.append(f"{m.name} {m.value()}")
            return "\n".join(lines) + "\n"


DEFAULT = Registry()

jobs_created = DEFAULT.counter(
    "tpujob_operator_jobs_created_total", "Total TrainJobs observed as created"
)
jobs_deleted = DEFAULT.counter(
    "tpujob_operator_jobs_deleted_total", "Total TrainJobs deleted"
)
jobs_successful = DEFAULT.counter(
    "tpujob_operator_jobs_successful_total", "Total TrainJobs that succeeded"
)
jobs_failed = DEFAULT.counter(
    "tpujob_operator_jobs_failed_total", "Total TrainJobs that failed"
)
jobs_restarted = DEFAULT.counter(
    "tpujob_operator_jobs_restarted_total", "Total TrainJobs that entered Restarting"
)
is_leader = DEFAULT.gauge(
    "tpujob_operator_is_leader", "1 when this operator instance holds leadership"
)
reconcile_total = DEFAULT.counter(
    "tpujob_operator_reconcile_total", "Total reconcile passes"
)
reconcile_errors = DEFAULT.counter(
    "tpujob_operator_reconcile_errors_total", "Total reconcile passes that errored"
)
reconcile_latency = DEFAULT.histogram(
    "tpujob_operator_reconcile_duration_seconds",
    "Per-reconcile sync latency (ref controller.go:289-291 logs this; "
    "here it is a scrapeable histogram)",
)
