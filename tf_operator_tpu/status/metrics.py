"""Operator metrics: Prometheus-compatible counters/gauges/histograms.

Capability parity with the reference's prometheus client usage:
tpujob_operator_jobs_{created,deleted,successful,failed,restarted}_total
(ref job.go:30-34, controller.go:68-72, status.go:46-58) and the leader gauge
(server.go:62-66), exposed in Prometheus text format by cli/server.py.

Round 8 adds the two pieces the reference's client had that the parity
port lacked:

  * **Labels**: `Counter/Gauge/Histogram.labels(**kv)` returns a child
    series keyed by the label set (the prometheus_client `labels()`
    contract), so per-namespace job counts and per-job trainer gauges
    (telemetry/collector.py's tpujob_trainer_*) are possible at all.
    A metric used both bare and labeled exposes both series under one
    family; a metric that only ever handed out children exposes no bare
    sample (a spurious 0-valued aggregate would double-count in sum()).
  * **Normalized exposition**: every family emits `# HELP` (even when
    the help text is empty) then `# TYPE` then its samples, with label
    values and help text escaped per the Prometheus text-format rules
    (backslash, double-quote, newline). tests/test_metrics.py pins the
    format with a parser round-trip.
"""

from __future__ import annotations

import threading


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: dict[str, str]) -> str:
    """{} -> "", else {a="x",b="y"} sorted by key (deterministic output;
    Prometheus treats label order as insignificant)."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _labelset_key(kv: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in kv.items()))


class Counter:
    _kind = "counter"

    def __init__(self, name: str, help_text: str,
                 label_values: dict[str, str] | None = None,
                 labels_only: bool = False):
        self.name = name
        self.help = help_text
        self._v = 0.0
        self._lock = threading.Lock()
        self._label_values = dict(label_values or {})
        self._children: dict[tuple, Counter] = {}
        # Whether the bare (parent) series was ever written. A family
        # with labeled children exposes its bare sample only if someone
        # actually inc()/set() it directly — never a phantom 0. A family
        # declared labels_only never exposes a bare sample at all (it
        # would otherwise show a meaningless 0 until the first child
        # exists, then vanish mid-life — a spurious stale series).
        self._touched = False
        self._labels_only = labels_only

    def labels(self, **kv) -> "Counter":
        """Child series for this label set (created on first use, cached:
        repeated labels(...) with the same values returns the same child,
        so increments accumulate)."""
        if not kv:
            raise ValueError("labels() requires at least one label")
        key = _labelset_key(kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help, label_values=dict(key))
                self._children[key] = child
            return child

    def remove(self, **kv) -> None:
        """Drop the child series for this label set (no-op when absent).
        Prometheus clients expose this for bounded cardinality: series
        keyed by a finite-lifetime entity (a job) must disappear when the
        entity does, or the family grows without bound."""
        with self._lock:
            self._children.pop(_labelset_key(kv), None)

    def labelsets(self) -> list[dict]:
        """The label sets of every live child series (for pruning)."""
        with self._lock:
            return [dict(k) for k in self._children]

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n
            self._touched = True

    def value(self) -> float:
        with self._lock:
            return self._v

    def _sample_lines(self) -> list[str]:
        return [f"{self.name}{_fmt_labels(self._label_values)} {self.value()}"]

    def expose_lines(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}".rstrip(),
            f"# TYPE {self.name} {self._kind}",
        ]
        with self._lock:
            children = list(self._children.values())
            touched = self._touched
        if touched or (not children and not self._labels_only):
            lines.extend(self._sample_lines())
        for c in children:
            lines.extend(c._sample_lines())
        return lines


class Gauge(Counter):
    _kind = "gauge"

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v
            self._touched = True


class Histogram:
    """Cumulative-bucket histogram, Prometheus semantics: each `le` bucket
    counts observations <= its bound, plus +Inf / _sum / _count series.
    The reference logs per-reconcile sync latency (controller.go:289-291);
    this surfaces the same signal as a scrapeable distribution."""

    _kind = "histogram"

    # Reconcile passes are ms-scale in-memory and seconds-scale against a
    # real apiserver; buckets span both.
    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 label_values: dict[str, str] | None = None,
                 labels_only: bool = False):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._lock = threading.Lock()
        self._label_values = dict(label_values or {})
        self._children: dict[tuple, Histogram] = {}
        self._touched = False
        self._labels_only = labels_only

    def labels(self, **kv) -> "Histogram":
        """Child histogram for this label set (shares the bucket layout)."""
        if not kv:
            raise ValueError("labels() requires at least one label")
        key = _labelset_key(kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, buckets=self.buckets,
                                  label_values=dict(key))
                self._children[key] = child
            return child

    def remove(self, **kv) -> None:
        """Drop the child series for this label set (see Counter.remove)."""
        with self._lock:
            self._children.pop(_labelset_key(kv), None)

    def labelsets(self) -> list[dict]:
        with self._lock:
            return [dict(k) for k in self._children]

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._touched = True
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def bucket_counts(self) -> list[int]:
        """Per-bucket (non-cumulative) counts, last entry = +Inf — the
        public surface percentile estimators (tools/exp_fleet.py) read,
        so delta-percentiles don't poke at _counts."""
        with self._lock:
            return list(self._counts)

    def _sample_lines(self) -> list[str]:
        with self._lock:
            counts = list(self._counts)
            total = self._sum
        base = dict(self._label_values)
        lines = []
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            lines.append(
                f"{self.name}_bucket{_fmt_labels({**base, 'le': str(b)})} {cum}")
        cum += counts[-1]
        lines.append(
            f"{self.name}_bucket{_fmt_labels({**base, 'le': '+Inf'})} {cum}")
        suffix = _fmt_labels(base)
        lines.append(f"{self.name}_sum{suffix} {total}")
        lines.append(f"{self.name}_count{suffix} {cum}")
        return lines

    def expose_lines(self) -> list[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}".rstrip(),
            f"# TYPE {self.name} {self._kind}",
        ]
        with self._lock:
            children = list(self._children.values())
            touched = self._touched
        if touched or (not children and not self._labels_only):
            lines.extend(self._sample_lines())
        for c in children:
            lines.extend(c._sample_lines())
        return lines


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "",
                labels_only: bool = False) -> Counter:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Counter(name, help_text,
                                              labels_only=labels_only)
            return self._metrics[name]

    def gauge(self, name: str, help_text: str = "",
              labels_only: bool = False) -> Gauge:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Gauge(name, help_text,
                                            labels_only=labels_only)
            m = self._metrics[name]
            assert isinstance(m, Gauge)
            return m

    def histogram(self, name: str, help_text: str = "",
                  labels_only: bool = False,
                  buckets: tuple[float, ...] = Histogram.DEFAULT_BUCKETS,
                  ) -> Histogram:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Histogram(name, help_text,
                                                buckets=buckets,
                                                labels_only=labels_only)
            m = self._metrics[name]
            assert isinstance(m, Histogram)
            return m

    def names(self) -> list[str]:
        """Every registered metric family name (tools/check_metrics_doc.py
        audits docs/monitoring.md against this)."""
        with self._lock:
            return sorted(self._metrics)

    def expose(self) -> str:
        """Prometheus text exposition format."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.expose_lines())
        return "\n".join(lines) + "\n"


DEFAULT = Registry()

jobs_created = DEFAULT.counter(
    "tpujob_operator_jobs_created_total",
    "Total TrainJobs observed as created (by namespace)",
    labels_only=True,
)
jobs_deleted = DEFAULT.counter(
    "tpujob_operator_jobs_deleted_total", "Total TrainJobs deleted (by namespace)",
    labels_only=True,
)
jobs_successful = DEFAULT.counter(
    "tpujob_operator_jobs_successful_total",
    "Total TrainJobs that succeeded (by namespace)",
    labels_only=True,
)
jobs_failed = DEFAULT.counter(
    "tpujob_operator_jobs_failed_total",
    "Total TrainJobs that failed (by namespace)",
    labels_only=True,
)
jobs_restarted = DEFAULT.counter(
    "tpujob_operator_jobs_restarted_total",
    "Total TrainJobs that entered Restarting (by namespace)",
    labels_only=True,
)
# Per-REPLICA restarts by cause — the jobs_restarted condition counter
# can't distinguish a preempted fleet (normal on TPUs, scale capacity)
# from a crash-looping image (page someone): reason=preempt (killed by an
# infrastructure signal: 130/137/143...), exit_code (retryable
# app-declared code, e.g. 138), backoff (kubelet in-place Always/
# OnFailure restart, the kind pastBackoffLimit counts), hang (the
# progress-heartbeat watchdog declared a Running job wedged and
# gang-restarted it — round 10). A gang restart increments ONCE however
# many pods it rolls.
restarts_total = DEFAULT.counter(
    "tpujob_restarts_total",
    "Replica restarts by cause (reason: preempt | exit_code | backoff | hang)",
    labels_only=True,
)
# Elastic recovery (recovery.elastic): one sample per gang reshape
# transition the controller admits — direction=shrink (re-admitted below
# spec size on degraded capacity) or grow (scaled back toward full size
# when capacity freed). The trainer's subsequent restore reshards the
# checkpoint onto the new mesh (models/checkpoint.py sharding manifests).
restore_reshard_total = DEFAULT.counter(
    "tpujob_restore_reshard_total",
    "Gang reshape transitions admitted (direction: shrink | grow); the "
    "resumed trainers reshard their checkpoint onto the new gang shape",
    labels_only=True,
)
gang_size = DEFAULT.gauge(
    "tpujob_gang_size",
    "Effective gang size (SPMD replica count) the controller is currently "
    "reconciling toward, per job — diverges from the spec while a "
    "GangReshaped job runs degraded",
    labels_only=True,
)
is_leader = DEFAULT.gauge(
    "tpujob_operator_is_leader", "1 when this operator instance holds leadership"
)
reconcile_total = DEFAULT.counter(
    "tpujob_operator_reconcile_total", "Total reconcile passes"
)
reconcile_errors = DEFAULT.counter(
    "tpujob_operator_reconcile_errors_total", "Total reconcile passes that errored"
)
reconcile_latency = DEFAULT.histogram(
    "tpujob_operator_reconcile_duration_seconds",
    "Per-reconcile sync latency (ref controller.go:289-291 logs this; "
    "here it is a scrapeable histogram)",
)
# Round 17 (control plane at 10k jobs): the write-path budget. requests
# counts every unary apiserver call the operator issues, by verb and
# resource kind — the denominator of "writes per job" the fleet bench
# gates on. coalesced counts status flushes the StatusWriter did NOT
# send: reason=noop (sync changed nothing -> zero requests) or
# reason=deferred (dirty, merged into a later write inside the
# coalescing window).
apiserver_requests = DEFAULT.counter(
    "tpujob_apiserver_requests_total",
    "Unary apiserver requests issued by the operator, by verb and "
    "resource kind (watch streams excluded)",
    labels_only=True,
)
status_writes_coalesced = DEFAULT.counter(
    "tpujob_status_writes_coalesced_total",
    "Status flushes skipped by the coalescing StatusWriter: reason=noop "
    "(nothing changed since observation) or reason=deferred (merged "
    "into a later write inside the coalescing window)",
    labels_only=True,
)
# Round 18 (flight recorder): phase durations derived from the lifecycle
# journal (telemetry/journal.py), observed once per transition at the
# controller — never per reconcile. phase=admission (submit -> slice
# admitted), scheduling (admitted -> Running condition), startup
# (Running -> first trainer step seen by the heartbeat source), recovery
# (gang roll / preemption -> back to Running; the restart MTTR). Fleet
# benches (tools/exp_fleet.py) gate admission p99 from this family
# instead of inferring it from wall clock.
job_phase_seconds = DEFAULT.histogram(
    "tpujob_job_phase_seconds",
    "Job lifecycle phase durations from the flight-recorder journal "
    "(phase: admission | scheduling | startup | recovery)",
    labels_only=True,
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0,
             1800.0),
)

# --- Fleet scheduler (sched/): admission, fair-share queueing, preemption.
sched_queue_depth = DEFAULT.gauge(
    "tpujob_sched_queue_depth",
    "TrainJobs waiting for slice capacity or quota, by scheduler queue",
    labels_only=True,
)
sched_admitted_total = DEFAULT.counter(
    "tpujob_sched_admitted_total",
    "Slice admissions granted by the fleet scheduler, by queue",
    labels_only=True,
)
sched_preemptions_total = DEFAULT.counter(
    "tpujob_sched_preemptions_total",
    "Graceful preemptions executed (victim evicted via SIGTERM -> "
    "emergency checkpoint -> requeue), by victim namespace",
    labels_only=True,
)
sched_quota_blocked_total = DEFAULT.counter(
    "tpujob_sched_quota_blocked_total",
    "Admission decisions deferred because the namespace ResourceQuota "
    "(maxSlices/maxJobs) was exhausted (one sample per deferred decision)",
    labels_only=True,
)
sched_queue_wait_seconds = DEFAULT.histogram(
    "tpujob_sched_queue_wait_seconds",
    "Submit-to-admission wait of slice jobs through the fair-share queue",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0),
)

# --- Serving (serve/): the InferenceService workload kind. The four
# tpujob_serve_* request families are emitted by the SERVER process
# (serve/server.py) on its own /metrics port, one child series per
# replica; defining them here keeps one registry as the source of truth
# the metrics-doc CI guard audits. The operator-side families
# (ready_replicas, scale_events) are emitted by serve/controller.py.
serve_requests_total = DEFAULT.counter(
    "tpujob_serve_requests_total",
    "Inference requests accepted by a serving replica (per replica)",
    labels_only=True,
)
serve_inflight = DEFAULT.gauge(
    "tpujob_serve_inflight",
    "Requests accepted but not yet answered on a serving replica — the "
    "autoscaler's load signal (per replica)",
    labels_only=True,
)
serve_batch_size = DEFAULT.histogram(
    "tpujob_serve_batch_size",
    "Rows per dispatched micro-batch (assembly under batchTimeoutMs up "
    "to batchMaxSize)",
    labels_only=True,
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
serve_latency_seconds = DEFAULT.histogram(
    "tpujob_serve_latency_seconds",
    "Request latency: accept -> response ready (queue wait + batch "
    "assembly + jitted forward + demux)",
    labels_only=True,
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0),
)
serve_pad_efficiency = DEFAULT.gauge(
    "tpujob_serve_pad_efficiency",
    "Useful units / padded units dispatched by a serving replica "
    "(cumulative; 1.0 = every padded slot carried real work). "
    "Classifiers count rows; generative models count rows + tokens, so "
    "this is the combined 2-D bucketing win signal. Pad-to-max under "
    "light load reads 1/batchMaxSize, bucketed reads near 1.0",
    labels_only=True,
)
serve_token_pad_efficiency = DEFAULT.gauge(
    "tpujob_serve_token_pad_efficiency",
    "Token-dimension slice of pad efficiency on a generative replica: "
    "useful tokens / padded token slots across prefill (seq-len "
    "bucketing win) and decode ticks (slot occupancy)",
    labels_only=True,
)
serve_tokens_total = DEFAULT.counter(
    "tpujob_serve_tokens_total",
    "Tokens generated by a serving replica (prefill first-tokens + one "
    "per active slot per decode tick) — the numerator of tokens/sec",
    labels_only=True,
)
serve_decode_steps_total = DEFAULT.counter(
    "tpujob_serve_decode_steps_total",
    "Decode ticks executed by the continuous-batching scheduler (each "
    "tick advances every active KV slot by one token)",
    labels_only=True,
)
serve_active_slots = DEFAULT.gauge(
    "tpujob_serve_active_slots",
    "KV-cache slots holding an in-flight sequence on a generative "
    "replica (of serving.maxConcurrentSequences) — feeds the router's "
    "least-loaded choice and the autoscaler load signal",
    labels_only=True,
)
serve_router_requests_total = DEFAULT.counter(
    "tpujob_serve_router_requests_total",
    "Requests the front-end router forwarded, per backend replica "
    "(least-time-averaged-inflight choice over READY replicas)",
    labels_only=True,
)
serve_router_hedges_total = DEFAULT.counter(
    "tpujob_serve_router_hedges_total",
    "Hedged sends at the front-end router tier (result: won = the "
    "duplicate answered first | lost = the primary did | suppressed = "
    "the budget expired but the tier was saturated, so no duplicate "
    "was launched). Read-timeouts never hedge",
    labels_only=True,
)
serve_router_affinity_total = DEFAULT.counter(
    "tpujob_serve_router_affinity_total",
    "Session-keyed routing decisions (result: hit = the consistent-hash "
    "ring's home replica was ready and chosen | miss = no ready home, "
    "fell back to least-loaded). hit/(hit+miss) is the affinity hit "
    "ratio — it should stay ~1 outside replica churn",
    labels_only=True,
)
serve_router_ready = DEFAULT.gauge(
    "tpujob_serve_router_ready",
    "Live front-end routers in the service's tier (of "
    "spec.serving.routers; below target means a router died and the "
    "controller is replacing it on the next tick)",
    labels_only=True,
)
serve_ckpt_follow_total = DEFAULT.counter(
    "tpujob_serve_ckpt_follow_total",
    "Checkpoint-follow hot-swaps (result: swapped | error). A swap "
    "replaces the served params between batches with no restart and no "
    "recompile",
    labels_only=True,
)
serve_ready_replicas = DEFAULT.gauge(
    "tpujob_serve_ready_replicas",
    "Running server replicas per InferenceService (operator-side; series "
    "removed when the service is deleted)",
    labels_only=True,
)
serve_scale_events_total = DEFAULT.counter(
    "tpujob_serve_scale_events_total",
    "Autoscale decisions applied (direction: up | down)",
    labels_only=True,
)
