"""Deterministic fault-spec parsing for the chaos-injection subsystem.

One string — the `TPUJOB_CHAOS` env var or the trainer's `--chaos` flag —
declares every fault a run should suffer, so a failure scenario is
reproducible from the job spec alone (the same philosophy as the fake
workload's `/exit?exitCode=N` hook, scaled to the whole stack).

Grammar (whitespace-insensitive):

    spec       := directive (";" directive)*
    directive  := kind (":" kv ("," kv)*)?
    kv         := key "=" value

Directive kinds and their keys (all integers/floats unless noted):

    kill       step=N signal=NAME     SIGTERM the trainer once it completes
               [replica=TYPE]         step N (signal: TERM/INT/USR1/KILL/
               [index=I]              SEGV..., bare name, SIG-prefixed, or
               [slice=K]              a number). Without a one-shot state
                                      dir the directive only fires in a
                                      process that STARTED before step N,
                                      so a resumed run past N never
                                      re-fires. replica/index restrict the
                                      directive to the pod whose
                                      TPUJOB_REPLICA_TYPE / _INDEX match —
                                      how a multi-worker job kills exactly
                                      one gang member. slice=K matches
                                      TPUJOB_SLICE_ID (multi-slice jobs:
                                      fail exactly one slice's gang;
                                      composes with replica/index to name
                                      one member of that slice).
    hang       step=N [duration=S]    stop stepping WITHOUT exiting after
               [replica=TYPE]         step N (the wedged-collective
               [index=I]              failure mode exit codes can never
                                      see — drives the heartbeat hang
                                      watchdog). No duration = hang until
                                      killed; with duration=S stepping
                                      resumes after S seconds. Same
                                      one-shot/replica semantics as kill.
    torn       step=N mode=truncate   corrupt the just-written checkpoint
                    |unlink           for step N (truncate the largest
                                      file to half, or unlink a leaf) —
                                      the resume-fallback scenario.
    stall      delay=S batch=N        sleep S seconds in the staging
                    | every=K         ring's transfer leg for batch N
               [lane=L]               (or every Kth batch). lane=L
                    | ckpt=N          restricts the stall to transfer
                                      lane L of the multi-lane engine
                                      (how a test wedges ONE lane and
                                      proves the others keep the ring
                                      ordered and live); lane=L alone
                                      (no batch/every) stalls every
                                      batch that lane carries. ckpt=N
                                      targets the CHECKPOINT WRITER
                                      instead of the staging ring: the
                                      save of step N sleeps S seconds
                                      between its finished tmp write
                                      and the publishing rename —
                                      deterministically holds the async
                                      write leg mid-write so a kill:
                                      landing there strands exactly one
                                      orbax tmp dir. ckpt= composes with
                                      nothing else (no batch/every/
                                      lane) and is one-shot like kill
                                      (per process without a
                                      TPUJOB_CHAOS_STATE dir, across
                                      restarts with one — a resumed
                                      generation re-saving step N must
                                      not re-stall).
    apiserver  errors=N code=C        the fake apiserver fails the next N
               latency=S match=SUB    matched requests with HTTP C
                                      (code=0: latency only), sleeping S
                                      first; match is a substring of
                                      "METHOD /path".
    preempt    step=N job=NAME        OPERATOR-side: the controller
               [namespace=NS]         gracefully evicts the named job
                                      (SIGTERM -> emergency checkpoint ->
                                      requeue, Preempted condition, tally
                                      untouched) once its progress
                                      heartbeat reaches step N — the
                                      deterministic stand-in for a
                                      higher-priority arrival, so
                                      preemption e2es fire at an exact
                                      step boundary like kill/hang.
                                      Requires a heartbeat source
                                      (operator --log-dir). namespace
                                      defaults to "default". One-shot
                                      like kill/hang.

    capacity   slices=N               OPERATOR-side: dial the fake slice
               [at_step=S job=NAME]   inventory to its first N entries
               [namespace=NS]         (slices at index >= N go offline;
                                      a later directive with a larger N
                                      brings them back — the
                                      deterministic stand-in for node
                                      loss/return in degraded-capacity
                                      e2es). Held slices are not
                                      revoked; the holder notices at
                                      its next gang roll (elastic
                                      recovery then reshapes onto
                                      whatever fits). Without at_step
                                      the dial describes inventory
                                      STATE and re-applies at EVERY
                                      operator start (a failover must
                                      not restore capacity the scenario
                                      lost); with at_step=S it fires
                                      once job=NAME's heartbeat reaches
                                      S (one-shot, like preempt).

One-shot semantics across restarts: when `TPUJOB_CHAOS_STATE` names a
directory, each fired directive drops a marker file there and never fires
again — `kill:step=5;kill:step=12` then kills a job exactly twice across
three process generations.

Parsing is strict (unknown kinds/keys and malformed values raise
ValueError with the offending token) so a typo'd fault spec fails the run
immediately instead of silently injecting nothing.
"""

from __future__ import annotations

import os
import signal as _signal
from dataclasses import dataclass, field

ENV_CHAOS = "TPUJOB_CHAOS"
ENV_CHAOS_STATE = "TPUJOB_CHAOS_STATE"

KINDS = ("kill", "hang", "torn", "stall", "apiserver", "preempt",
         "capacity")

_KEYS: dict[str, dict[str, type]] = {
    "kill": {"step": int, "signal": str, "replica": str, "index": int,
             "slice": int},
    "hang": {"step": int, "duration": float, "replica": str, "index": int,
             "slice": int},
    "torn": {"step": int, "mode": str},
    "stall": {"delay": float, "batch": int, "every": int, "lane": int,
              "ckpt": int},
    "apiserver": {"errors": int, "code": int, "latency": float,
                  "match": str},
    "preempt": {"step": int, "job": str, "namespace": str},
    "capacity": {"slices": int, "at_step": int, "job": str,
                 "namespace": str},
}

TORN_MODES = ("truncate", "unlink")


def parse_signal(name: str) -> int:
    """'TERM' / 'SIGTERM' / '15' -> 15. Raises ValueError on unknowns."""
    s = name.strip().upper()
    if s.isdigit():
        return int(s)
    if not s.startswith("SIG"):
        s = "SIG" + s
    try:
        return int(getattr(_signal, s))
    except AttributeError:
        raise ValueError(f"unknown signal {name!r}") from None


@dataclass(frozen=True)
class Directive:
    kind: str
    params: dict = field(default_factory=dict)

    @property
    def id(self) -> str:
        """Stable identity for one-shot markers: kind plus its sorted
        params ('kill.signal=TERM.step=5')."""
        parts = [self.kind] + [
            f"{k}={self.params[k]}" for k in sorted(self.params)
        ]
        return ".".join(parts)


def parse_chaos(text: str) -> list[Directive]:
    """Parse a chaos spec string; [] for empty/blank input."""
    out: list[Directive] = []
    for raw in (text or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, _, rest = raw.partition(":")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"chaos: unknown directive kind {kind!r} (not in {KINDS})"
            )
        params: dict = {}
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, sep, value = kv.partition("=")
            key = key.strip()
            if not sep:
                raise ValueError(f"chaos: {kind}: expected key=value, got {kv!r}")
            typ = _KEYS[kind].get(key)
            if typ is None:
                raise ValueError(
                    f"chaos: {kind}: unknown key {key!r} "
                    f"(valid: {sorted(_KEYS[kind])})"
                )
            try:
                params[key] = typ(value.strip())
            except ValueError:
                raise ValueError(
                    f"chaos: {kind}: {key}={value.strip()!r} is not a "
                    f"valid {typ.__name__}"
                ) from None
        _validate(kind, params)
        out.append(Directive(kind, params))
    return out


def _validate(kind: str, params: dict) -> None:
    if kind in ("kill", "hang") and params.get("index", 0) < 0:
        raise ValueError(f"chaos: {kind}: index must be >= 0")
    if kind in ("kill", "hang") and params.get("slice", 0) < 0:
        raise ValueError(f"chaos: {kind}: slice must be >= 0")
    if kind == "kill":
        if "step" not in params:
            raise ValueError("chaos: kill requires step=N")
        parse_signal(params.get("signal", "TERM"))  # fail fast on typos
    elif kind == "hang":
        if "step" not in params:
            raise ValueError("chaos: hang requires step=N")
        if params.get("duration", 1.0) <= 0:
            raise ValueError("chaos: hang: duration must be > 0")
    elif kind == "torn":
        if "step" not in params:
            raise ValueError("chaos: torn requires step=N")
        mode = params.get("mode", "truncate")
        if mode not in TORN_MODES:
            raise ValueError(
                f"chaos: torn: mode {mode!r} not in {TORN_MODES}"
            )
    elif kind == "stall":
        if "delay" not in params or params["delay"] < 0:
            raise ValueError("chaos: stall requires delay=SECONDS >= 0")
        if "batch" in params and "every" in params:
            raise ValueError(
                "chaos: stall takes at most one of batch=N or every=K"
            )
        if "ckpt" in params and any(
                k in params for k in ("batch", "every", "lane")):
            raise ValueError(
                "chaos: stall: ckpt=N targets the checkpoint writer and "
                "composes with none of batch/every/lane"
            )
        if ("batch" not in params and "every" not in params
                and "lane" not in params and "ckpt" not in params):
            raise ValueError(
                "chaos: stall needs a target: batch=N, every=K, lane=L, "
                "or ckpt=N"
            )
        if params.get("every", 1) < 1:
            raise ValueError("chaos: stall: every must be >= 1")
        if params.get("lane", 0) < 0:
            raise ValueError("chaos: stall: lane must be >= 0")
        if params.get("ckpt", 1) < 1:
            raise ValueError("chaos: stall: ckpt must be >= 1 (saves "
                             "happen at completed-step boundaries)")
    elif kind == "apiserver":
        if params.get("errors", 1) < 0:
            raise ValueError("chaos: apiserver: errors must be >= 0")
        if params.get("latency", 0.0) < 0:
            raise ValueError("chaos: apiserver: latency must be >= 0")
    elif kind == "preempt":
        if "step" not in params:
            raise ValueError("chaos: preempt requires step=N")
        if not params.get("job"):
            raise ValueError("chaos: preempt requires job=NAME")
    elif kind == "capacity":
        if "slices" not in params or params["slices"] < 0:
            raise ValueError("chaos: capacity requires slices=N >= 0")
        if "at_step" in params and not params.get("job"):
            raise ValueError(
                "chaos: capacity: at_step=S needs job=NAME (the step is "
                "observed on that job's progress heartbeat)"
            )


def from_env(env: dict | None = None) -> list[Directive]:
    """Directives from TPUJOB_CHAOS; [] when unset. Strict parse: a bad
    spec raises rather than running the job un-faulted."""
    e = os.environ if env is None else env
    return parse_chaos(e.get(ENV_CHAOS, ""))


class OneShotState:
    """Marker-file store making directives fire once across process
    restarts (TPUJOB_CHAOS_STATE). Without a configured directory, fired()
    is process-local memory — each new process starts fresh."""

    def __init__(self, state_dir: str | None = None):
        self.state_dir = state_dir
        self._fired: set[str] = set()
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)

    @classmethod
    def from_env(cls, env: dict | None = None) -> "OneShotState":
        e = os.environ if env is None else env
        return cls(e.get(ENV_CHAOS_STATE) or None)

    def _path(self, directive_id: str) -> str:
        # Marker names must be filesystem-safe; directive ids are
        # [a-z0-9.=_-] by construction (kind + key=value tokens).
        return os.path.join(self.state_dir or "", directive_id + ".fired")

    def fired(self, directive: Directive) -> bool:
        if directive.id in self._fired:
            return True
        return bool(self.state_dir) and os.path.exists(
            self._path(directive.id)
        )

    def mark(self, directive: Directive) -> None:
        self._fired.add(directive.id)
        if self.state_dir:
            with open(self._path(directive.id), "w") as f:
                f.write("1")
