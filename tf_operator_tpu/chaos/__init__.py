"""Fault injection for the full stack, driven by one deterministic spec.

The reference's robustness story was tested with a controllable fake
workload (`/exit?exitCode=N`); real TPU fleets fail in richer ways —
preemption signals mid-step, torn checkpoint writes, flaky apiservers,
stalled host->device links. This package turns each of those into a
declarative, reproducible fault (spec grammar in `chaos/spec.py`):

    TPUJOB_CHAOS="kill:step=12,signal=TERM"            # preempt at step 12
    TPUJOB_CHAOS="torn:step=8;kill:step=8,signal=KILL" # tear then die
    TPUJOB_CHAOS="stall:every=3,delay=0.2"             # slow transfer link
    TPUJOB_CHAOS="apiserver:errors=2,code=503"         # flaky control plane

Injection points (each a one-line hook at the subsystem's natural
boundary, zero-cost when TPUJOB_CHAOS is unset):

  * trainer step boundary       models/train.py  -> TrainerChaos.maybe_kill
  * checkpoint write            models/train.py  -> tear_checkpoint
  * staging-ring transfer leg   data/staging.py  -> staging_stall_delay
  * apiserver request handling  testing/fake_apiserver.py inject_faults
    (the fake reads `apiserver:` directives; core/k8s.py's bounded
    jittered retry is what the injected 5xx/409s exercise)

tests/test_chaos.py drives the capstone: chaos SIGTERMs a trainer
mid-run, the operator's EXIT_CODE policy restarts the pod, and the
resumed run finishes at the exact final step on the uninterrupted loss
trajectory.
"""

from __future__ import annotations

import os

from tf_operator_tpu.chaos.spec import (
    ENV_CHAOS,
    ENV_CHAOS_STATE,
    Directive,
    OneShotState,
    from_env,
    parse_chaos,
    parse_signal,
)

__all__ = [
    "ENV_CHAOS", "ENV_CHAOS_STATE", "Directive", "OneShotState",
    "from_env", "parse_chaos", "parse_signal",
    "TrainerChaos", "hang", "tear_checkpoint", "staging_stalls_from_env",
    "staging_stall_delay", "ckpt_stalls_from_env", "ckpt_stall_delay",
    "reset_ckpt_stall_state", "apiserver_directives", "preempt_directives",
    "capacity_directives",
]


def replica_matches(directive: Directive, env: dict | None = None) -> bool:
    """Whether a kill/hang directive targets THIS replica. Directives may
    carry `replica=TYPE` / `index=I` to name one gang member (how a
    multi-worker job kills exactly one peer); without them every process
    matches. A directive that names a replica never fires in a process the
    operator didn't label (standalone runs have no TPUJOB_REPLICA_* env)."""
    e = os.environ if env is None else env
    want_type = directive.params.get("replica")
    if want_type is not None:
        if e.get("TPUJOB_REPLICA_TYPE", "").lower() != want_type.lower():
            return False
    want_idx = directive.params.get("index")
    if want_idx is not None:
        try:
            if int(e.get("TPUJOB_REPLICA_INDEX", "")) != want_idx:
                return False
        except ValueError:
            return False
    # slice=K (multi-slice jobs): matches TPUJOB_SLICE_ID — how an e2e
    # fails exactly one slice's gang. Same never-fires-unlabeled rule as
    # replica/index: single-slice pods carry no slice id.
    want_slice = directive.params.get("slice")
    if want_slice is not None:
        try:
            if int(e.get("TPUJOB_SLICE_ID", "")) != want_slice:
                return False
        except ValueError:
            return False
    return True


def hang(duration: float | None) -> None:
    """Stop making progress without exiting — the wedged-collective
    simulation. Sleeps in short slices; duration=None hangs until killed
    from outside (SIGTERM only latches under the preemption guard — a real
    wedge never reaches its graceful path, so neither does this one; the
    runtime's drain discipline escalates to SIGKILL)."""
    import time as _time

    deadline = None if duration is None else _time.monotonic() + duration
    while deadline is None or _time.monotonic() < deadline:
        _time.sleep(0.25)


class TrainerChaos:
    """Trainer-side directives (kill / hang / torn), evaluated at step
    boundaries.

    Kill/hang semantics without a one-shot state dir: fire when this
    process both STARTED before the target step and has now completed it
    (start_step < step <= done) — a run resumed at/past the target step
    never re-fires, which is exactly the preempt->restart->resume e2e
    shape. With TPUJOB_CHAOS_STATE set, fired directives drop markers and
    the start_step guard is unnecessary (multi-kill scripts work; a hang
    job resumed from a checkpoint BEFORE the hang step needs the markers,
    since the gang restart replays those steps)."""

    def __init__(self, directives: list[Directive],
                 state: OneShotState | None = None):
        self.kills = [d for d in directives if d.kind == "kill"]
        self.hangs = [d for d in directives if d.kind == "hang"]
        self.tears = [d for d in directives if d.kind == "torn"]
        self.state = state or OneShotState()

    @classmethod
    def from_env(cls, env: dict | None = None) -> "TrainerChaos | None":
        """None when TPUJOB_CHAOS is unset/empty — the no-chaos fast path
        (one dict lookup; no object, no per-step work)."""
        directives = from_env(env)
        if not any(d.kind in ("kill", "hang", "torn") for d in directives):
            return None
        return cls(directives, OneShotState.from_env(env))

    def _due(self, directives: list[Directive], done: int,
             start_step: int) -> Directive | None:
        """First unfired directive whose step this boundary crossed and
        whose replica filter matches this process; marks it fired."""
        for d in directives:
            step = d.params["step"]
            if done < step or self.state.fired(d):
                continue
            if not self.state.state_dir and start_step >= step:
                continue  # resumed past the target point: never re-fire
            if not replica_matches(d):
                continue
            self.state.mark(d)
            return d
        return None

    def maybe_kill(self, done: int, start_step: int) -> None:
        """Deliver the configured signal to THIS process once step
        `done` >= the directive's step. Called after a step/chunk
        completes; a caught signal (TERM/INT/USR1 under the preemption
        guard) returns here and the caller's boundary check handles it —
        an uncaught one (KILL) never returns."""
        d = self._due(self.kills, done, start_step)
        if d is not None:
            os.kill(os.getpid(), parse_signal(d.params.get("signal", "TERM")))

    def hang_at(self, done: int, start_step: int) -> Directive | None:
        """The hang directive this boundary triggers, if any (marked
        fired). The caller emits its event and calls hang() — kept apart
        so the trainer can record the hang in its metrics stream first."""
        return self._due(self.hangs, done, start_step)

    def tear_for_step(self, step: int) -> Directive | None:
        """The torn-checkpoint directive for `step`, if any unfired."""
        for d in self.tears:
            if d.params["step"] == step and not self.state.fired(d):
                return d
        return None


def tear_checkpoint(ckpt_dir: str, step: int, mode: str = "truncate") -> str:
    """Corrupt the finished checkpoint for `step` the way real storage
    failures do: `truncate` halves the largest file (a torn write the
    manifest's size census catches); `unlink` removes a leaf (a lost
    object / missing directory). Returns the damaged path."""
    import shutil

    root = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    if not os.path.isdir(root):
        raise FileNotFoundError(root)
    files: list[tuple[int, str]] = []
    subdirs: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        for d in dirnames:
            subdirs.append(os.path.join(dirpath, d))
        for f in filenames:
            p = os.path.join(dirpath, f)
            files.append((os.path.getsize(p), p))
    if mode == "unlink":
        if subdirs:
            target = sorted(subdirs)[0]
            shutil.rmtree(target)
            return target
        if files:
            target = max(files)[1]
            os.unlink(target)
            return target
        raise FileNotFoundError(f"nothing to unlink under {root}")
    # truncate (default): the largest file torn to half its bytes.
    if not files:
        raise FileNotFoundError(f"nothing to truncate under {root}")
    size, target = max(files)
    with open(target, "r+b") as f:
        f.truncate(size // 2)
    return target


def staging_stalls_from_env(env: dict | None = None) -> list[Directive]:
    """`stall:` directives for data/staging.py's transfer thread; [] on
    the (overwhelmingly common) no-chaos path. ckpt-targeted stalls are
    excluded — they belong to the checkpoint writer, and the staging
    engine's lane-only fallthrough would otherwise fire them on every
    batch."""
    e = os.environ if env is None else env
    if not e.get(ENV_CHAOS):
        return []
    return [d for d in from_env(e)
            if d.kind == "stall" and "ckpt" not in d.params]


def staging_stall_delay(index: int, stalls: list[Directive],
                        lane: int | None = None) -> float:
    """Total injected sleep for staged batch `index` (0-based) when
    carried by transfer lane `lane`. A directive with `lane=L` fires only
    in that lane (None — callers predating the multi-lane engine — never
    matches a lane-targeted directive); `lane=L` with no batch/every
    stalls every batch the lane carries."""
    total = 0.0
    for d in stalls:
        want_lane = d.params.get("lane")
        if want_lane is not None and lane != want_lane:
            continue
        if "batch" in d.params:
            if index == d.params["batch"]:
                total += d.params["delay"]
        elif "every" in d.params:
            if index % d.params["every"] == 0:
                total += d.params["delay"]
        else:  # lane-only directive: every batch this lane carries
            total += d.params["delay"]
    return total


def ckpt_stalls_from_env(env: dict | None = None) -> list[Directive]:
    """`stall:ckpt=N` directives — the checkpoint writer's deterministic
    mid-write hold (models/checkpoint.py sleeps in the tmp->rename
    publish window); [] on the no-chaos path."""
    e = os.environ if env is None else env
    if not e.get(ENV_CHAOS):
        return []
    return [d for d in from_env(e)
            if d.kind == "stall" and "ckpt" in d.params]


# Run-lifetime one-shot memory for ckpt stalls (the env-state-dir
# variant persists across restarts on its own; without one, this cache is
# what makes "fires once per run" true across repeated saves). The
# trainer's teardown calls reset_ckpt_stall_state() so in-process callers
# (tests, notebooks) get fresh one-shot semantics — and a changed
# TPUJOB_CHAOS_STATE — on their next run, matching kill/hang (which
# rebuild their OneShotState per TrainerChaos.from_env).
_ckpt_stall_state: OneShotState | None = None


def reset_ckpt_stall_state() -> None:
    global _ckpt_stall_state
    _ckpt_stall_state = None


def ckpt_stall_delay(step: int, stalls: list[Directive],
                     state: OneShotState | None = None) -> float:
    """Total injected sleep for the checkpoint publishing step `step`.
    One-shot like kill/hang: a directive fires once per process (or once
    across restarts when TPUJOB_CHAOS_STATE marks it) — a resumed
    generation re-saving the same step must not re-stall, or a single
    mid-write kill scenario would wedge every retry after it."""
    global _ckpt_stall_state
    if not stalls:
        return 0.0
    if state is None:
        if _ckpt_stall_state is None:
            _ckpt_stall_state = OneShotState.from_env()
        state = _ckpt_stall_state
    total = 0.0
    for d in stalls:
        if d.params.get("ckpt") != step or state.fired(d):
            continue
        state.mark(d)
        total += d.params["delay"]
    return total


def apiserver_directives(env: dict | None = None) -> list[Directive]:
    """`apiserver:` directives (the fake apiserver's inject_faults feed)."""
    e = os.environ if env is None else env
    if not e.get(ENV_CHAOS):
        return []
    return [d for d in from_env(e) if d.kind == "apiserver"]


def preempt_directives(env: dict | None = None) -> list[Directive]:
    """`preempt:` directives — the operator-side eviction feed
    (core/trainjob_controller.py reads these at construction and evicts
    the named job once its heartbeat crosses the step)."""
    e = os.environ if env is None else env
    if not e.get(ENV_CHAOS):
        return []
    return [d for d in from_env(e) if d.kind == "preempt"]


def capacity_directives(env: dict | None = None) -> list[Directive]:
    """`capacity:` directives — the operator-side slice-inventory dial
    (core/trainjob_controller.py applies step-less ones at construction
    and polls at_step ones against the named job's heartbeat)."""
    e = os.environ if env is None else env
    if not e.get(ENV_CHAOS):
        return []
    return [d for d in from_env(e) if d.kind == "capacity"]
