"""ResNet family (v1.5 bottleneck) — the flagship vision model.

Parity target: the reference's MultiWorkerMirroredStrategy ResNet-50 baseline
(examples/v1/distribution_strategy, BASELINE.md workload 3), rebuilt for TPU:
bfloat16 compute end-to-end (MXU-native), f32 parameters and batch-norm
statistics, NHWC layout (XLA:TPU-preferred), and cross-replica batch-norm via
an optional axis_name so dp training matches single-device numerics.

ResNet-50 = [3, 4, 6, 3] bottleneck stages, 64..512 base widths, 7x7 stem —
the standard architecture (He et al. '15), v1.5 variant (stride on the 3x3).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class TpuBatchNorm(nn.Module):
    """bf16-resident batch norm (drop-in for nn.BatchNorm on NHWC convs).

    flax's nn.BatchNorm promotes the whole activation tensor to f32 inside
    its normalize step (y = x - mean with an f32 mean), dragging full-size
    f32 elementwise chains through HBM on every layer. Here the f32
    *per-channel* statistics are folded into per-channel scale/bias applied
    in the activation dtype (y = x * a + b), so no tensor-sized f32 op ever
    exists: the stats reductions ride the producing conv as a fused
    convert+reduce epilogue, and the fold is two C-sized vectors.

    Parameter/variable names match nn.BatchNorm ("scale"/"bias" params,
    "mean"/"var" batch_stats), so checkpoints are interchangeable.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: str | None = None
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    dtype: jnp.dtype = jnp.bfloat16        # accepted for API parity; the
    param_dtype: jnp.dtype = jnp.float32   # fold always runs in x.dtype

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", self.scale_init, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            m, v = ra_mean.value, ra_var.value
        else:
            red = tuple(range(x.ndim - 1))
            # Convert before squaring: E[x^2]-E[x]^2 cancels catastrophically
            # for |mean| >> std if the squares carry bf16 rounding. The
            # convert+square+reduce chain still fuses into the producing
            # conv's epilogue — no f32 tensor is materialized.
            xf = x.astype(jnp.float32)
            m = jnp.mean(xf, axis=red)
            m2 = jnp.mean(jax.lax.square(xf), axis=red)
            if self.axis_name is not None:
                m, m2 = jax.lax.pmean(jnp.stack([m, m2]), self.axis_name)
            v = jnp.maximum(m2 - jnp.square(m), 0.0)
            if not self.is_initializing():
                mom = self.momentum
                ra_mean.value = mom * ra_mean.value + (1.0 - mom) * m
                ra_var.value = mom * ra_var.value + (1.0 - mom) * v
        inv = scale * jax.lax.rsqrt(v + self.epsilon)
        # Subtract-then-scale, not a y = x*a + b fold: with |mean| >> std the
        # fold cancels catastrophically in bf16 (x*a and b are both huge, the
        # result small). x - mean is exact in bf16 for nearby magnitudes; the
        # tiny residual from rounding mean to bf16 is folded into the bias.
        mh = m.astype(x.dtype)
        a = inv.astype(x.dtype)
        b = (bias + (mh.astype(jnp.float32) - m) * inv).astype(x.dtype)
        return (x - mh) * a + b


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: jnp.dtype
    norm: partial

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1.
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN

        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.dtype, name="proj",
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_axis_name: str | None = None  # e.g. "dp" for cross-replica batch norm

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            TpuBatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype, name="stem",
        )(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=self.width * 2**i,
                    strides=2 if i > 0 and j == 0 else 1,
                    dtype=self.dtype,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2])  # basic-block depth kept
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])


def init_resnet(
    model: ResNet, rng: jax.Array, image_size: int = 224, batch: int = 2
):
    """Returns (params, batch_stats)."""
    variables = model.init(
        rng, jnp.zeros((batch, image_size, image_size, 3), jnp.float32), train=False
    )
    return variables["params"], variables.get("batch_stats", {})
