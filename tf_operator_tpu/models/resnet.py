"""ResNet family (v1.5 bottleneck) — the flagship vision model.

Parity target: the reference's MultiWorkerMirroredStrategy ResNet-50 baseline
(examples/v1/distribution_strategy, BASELINE.md workload 3), rebuilt for TPU:
bfloat16 compute end-to-end (MXU-native), f32 parameters and batch-norm
statistics, NHWC layout (XLA:TPU-preferred), and cross-replica batch-norm via
an optional axis_name so dp training matches single-device numerics.

ResNet-50 = [3, 4, 6, 3] bottleneck stages, 64..512 base widths, 7x7 stem —
the standard architecture (He et al. '15), v1.5 variant (stride on the 3x3).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: jnp.dtype
    norm: partial

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1.
        y = nn.Conv(
            self.filters, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=self.dtype,
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN

        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.dtype, name="proj",
            )(residual)
            residual = self.norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_axis_name: str | None = None  # e.g. "dp" for cross-replica batch norm

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.bn_axis_name,
        )
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype, name="stem",
        )(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=self.width * 2**i,
                    strides=2 if i > 0 and j == 0 else 1,
                    dtype=self.dtype,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2])  # basic-block depth kept
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])


def init_resnet(
    model: ResNet, rng: jax.Array, image_size: int = 224, batch: int = 2
):
    """Returns (params, batch_stats)."""
    variables = model.init(
        rng, jnp.zeros((batch, image_size, image_size, 3), jnp.float32), train=False
    )
    return variables["params"], variables.get("batch_stats", {})
