"""Mixture-of-Experts transformer with expert parallelism (`ep` mesh axis).

SURVEY.md §2's parallelism table lists EP as a strategy the reference has no
operator-side machinery for ("same: mesh axis") — the TPU build realizes it
in the data plane: expert weights are stacked `[E, ...]` tensors sharded over
the `ep` axis (parallel/mesh.py), and token routing is the GShard/Switch
dense-dispatch formulation — one-hot dispatch/combine einsums with a static
per-expert capacity — so every shape is static, the routing math lowers to
MXU-friendly batched matmuls, and XLA inserts the dp<->ep all-to-alls from
the sharding annotations alone (scaling-book recipe; no hand-written
collectives).

Naming contract for sharding rules (parallel/sharding_rules.MOE_RULES):
router/kernel, experts_in, experts_out (stacked expert FFN weights).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import AttnFn, SelfAttention


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    num_layers: int = 4
    hidden: int = 512
    num_heads: int = 8
    mlp_ratio: int = 4
    max_len: int = 1024
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_every: int = 2          # every Nth block uses the MoE MLP
    balance_coef: float = 1e-2  # Switch load-balancing aux loss weight
    zloss_coef: float = 1e-3    # router logit z-loss weight
    causal: bool = True
    dtype: jnp.dtype = jnp.bfloat16
    # Token->expert dispatch formulation:
    #   "dense"  — GShard one-hot einsums with static capacity. Every shape
    #              is expert-count-independent, so sharding the stacked
    #              expert weights over `ep` makes XLA insert the all-to-all;
    #              the price is dead compute (capacity padding) and the
    #              [B,T,E,C] dispatch/combine einsums themselves.
    #   "sparse" — sort-by-expert + ragged grouped matmul (Megablocks
    #              formulation): no capacity, no dropped tokens, no padding
    #              FLOPs. Experts must be local (ep=1) — the sorted layout
    #              is token-order-dependent, which GSPMD cannot re-shard
    #              automatically. This is the single-chip/ep=1 perf path
    #              (VERDICT r3 #2); dense stays the ep>1 path.
    dispatch: str = "dense"

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads

    @property
    def ffn(self) -> int:
        return self.hidden * self.mlp_ratio

    def capacity(self, seq_len: int) -> int:
        """Static per-expert token capacity C for a [B, T] batch row."""
        c = int(self.top_k * seq_len * self.capacity_factor / self.num_experts)
        return max(c, 1)


TINY_MOE = MoEConfig(
    vocab_size=1024, num_layers=2, hidden=128, num_heads=4, max_len=256,
    num_experts=4, top_k=2, moe_every=1,
)


def topk_routing(
    router_logits: jax.Array, top_k: int, capacity: int
) -> tuple[jax.Array, jax.Array, dict[str, jax.Array]]:
    """GShard top-k routing with static capacity.

    router_logits: [B, T, E] (float32). Returns:
      combine  [B, T, E, C] f32 — gate weight of token t in expert e, slot c
      dispatch [B, T, E, C] bool — combine > 0
      aux      dict arrays for the load-balance loss (f_e counts, p_e probs)

    Priority is choice-major (all 1st choices claim slots before any 2nd
    choice) then token-major — the GShard order, so earlier tokens win slots
    deterministically. Everything is one-hot einsums: no gather/scatter, no
    dynamic shapes.
    """
    b, t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    remaining = probs
    masks, gates = [], []
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)                   # [B, T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [B, T, E]
        masks.append(onehot)
        gates.append((remaining * onehot).sum(-1))             # [B, T]
        remaining = remaining * (1.0 - onehot)

    mask_k = jnp.stack(masks, axis=1)                          # [B, K, T, E]
    gate_k = jnp.stack(gates, axis=1)                          # [B, K, T]
    if top_k > 1:
        # Normalize the K selected gates to sum to 1 (top-2 convention).
        gate_k = gate_k / jnp.maximum(gate_k.sum(axis=1, keepdims=True), 1e-9)
    # top_k == 1 keeps the raw softmax prob (Switch eq. 2) — normalizing would
    # make every combine weight exactly 1.0 and cut the router out of the LM
    # loss's gradient path entirely.

    # Slot assignment: cumulative count over the flattened (K, T) order.
    flat = mask_k.reshape(b, top_k * t, e)
    pos_in_expert = jnp.cumsum(flat, axis=1) - flat            # [B, KT, E]
    pos = (pos_in_expert * flat).sum(-1)                       # [B, KT]
    fits = (pos < capacity)[..., None] * flat                  # [B, KT, E]
    slot_onehot = jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )                                                          # [B, KT, C]

    combine_flat = (
        gate_k.reshape(b, top_k * t)[..., None, None]
        * fits[..., None]
        * slot_onehot[:, :, None, :]
    )                                                          # [B, KT, E, C]
    combine = combine_flat.reshape(b, top_k, t, e, capacity).sum(axis=1)
    dispatch = combine > 0.0

    aux = {
        # fraction of tokens whose FIRST choice is expert e (Switch f_e)
        "fraction": mask_k[:, 0].mean(axis=(0, 1)),            # [E]
        "prob": probs.mean(axis=(0, 1)),                       # [E]
        "logits": router_logits,
    }
    return combine, dispatch, aux


def _dispatch_gather(xf, token_of, inv, k):
    """x_sorted[i] = xf[token_of[i]] where token_of = order // k duplicates
    every token top_k times then groups rows by expert.

    Plain jnp.take here makes XLA emit a [N*K, H] -> [N, H] scatter-add for
    the backward (it cannot see that the duplicate indices are a tiled
    permutation) — measured at ~9% of the sparse step on-chip. The VJP is
    written by hand instead: un-permute the cotangent with the inverse
    permutation (a gather) and sum the K copies of each token (a reduce).

    The index arrays and k are closed over rather than passed as formal
    custom_vjp arguments — only the differentiable operand is formal, so
    no None-cotangent convention or residual-carried k is needed
    (round-4 advice: that convention is fragile against JAX's custom_vjp
    cotangent checking).
    """
    n = xf.shape[0]

    @jax.custom_vjp
    def gather(x):
        return jnp.take(x, token_of, axis=0)

    def fwd(x):
        return jnp.take(x, token_of, axis=0), None

    def bwd(_, g):
        g_rep = jnp.take(g, inv, axis=0)           # row a <-> token a // k
        return (g_rep.reshape(n, k, g.shape[-1]).sum(axis=1),)

    gather.defvjp(fwd, bwd)
    return gather(xf)


def _permute_rows(x, perm, inv_perm):
    """y[i] = x[perm[i]] for a PERMUTATION perm with known inverse: the
    cotangent flows back through a gather by inv_perm instead of the
    duplicate-index scatter XLA emits for a generic take's transpose.
    perm/inv_perm are closed over (see _dispatch_gather)."""

    @jax.custom_vjp
    def permute(x):
        return jnp.take(x, perm, axis=0)

    def fwd(x):
        return jnp.take(x, perm, axis=0), None

    def bwd(_, g):
        return (jnp.take(g, inv_perm, axis=0),)

    permute.defvjp(fwd, bwd)
    return permute(x)


def _grouped_matmul(
    x: jax.Array, w: jax.Array, group_sizes: jax.Array,
    tiling: str | None = None,
) -> jax.Array:
    """[M, K] x [E, K, N] -> [M, N] where rows of x are grouped by expert
    (group_sizes[e] consecutive rows use w[e]).

    Default engine is `lax.ragged_dot` (XLA ragged dot, differentiable).
    TPUJOB_MOE_GMM=megablox swaps in the pallas megablocks gmm kernel
    (jax.experimental.pallas.ops.tpu.megablox) on TPU — kept switchable so
    the bench can measure both lowerings on the chip.
    """
    import os

    if os.environ.get("TPUJOB_MOE_GMM") == "megablox":
        # the package re-exports the gmm custom_vjp function itself
        from jax.experimental.pallas.ops.tpu.megablox import gmm as _gmm

        return _gmm(x, w, group_sizes.astype(jnp.int32))
    if tiling:
        from jax.experimental.xla_metadata import set_xla_metadata

        # Mosaic honors a ragged_dot_tiling=(m,k,n) frontend attribute;
        # standalone sweep (docs/perf.md) puts 4096,768,1024 ~8% over the
        # compiler's default on the fwd expert matmul. Per call site — a
        # K=768 tiling cannot compile the K=3072 matmul. Trace-time only,
        # so the AD-generated backward ragged dots keep compiler defaults.
        with set_xla_metadata(ragged_dot_tiling=tiling):
            return jax.lax.ragged_dot(x, w, group_sizes.astype(jnp.int32))
    return jax.lax.ragged_dot(x, w, group_sizes.astype(jnp.int32))


def sparse_moe_ffn(
    x: jax.Array,
    w_router: jax.Array,
    experts_in: jax.Array,
    experts_out: jax.Array,
    cfg: MoEConfig,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Dropless sorted dispatch (Megablocks): route, sort token copies by
    expert, run the expert FFNs as ragged grouped matmuls over contiguous
    groups, unsort, and gate-combine.

    Static shapes throughout: every token contributes exactly top_k rows
    ([N*K, H] workset), the per-expert split lives in `group_sizes` data —
    not in shapes — so jit traces once. No capacity limit: unlike the dense
    path nothing is dropped, which also makes this path agree exactly with
    `moe_reference_forward`. All data movement is gathers over a permutation
    (argsort + inverse), never duplicate-index scatters.
    """
    b, t, h = x.shape
    n = b * t
    k = cfg.top_k
    xf = x.reshape(n, h)

    # Both operands up-cast: under master_weights the live router param is
    # a bf16 compute copy, and routing decisions must stay f32 regardless.
    logits = xf.astype(jnp.float32) @ w_router.astype(jnp.float32)  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)                        # [N, K]
    if k > 1:
        topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    # top_k == 1 keeps the raw softmax prob (Switch eq. 2) — see topk_routing.

    flat_e = topi.reshape(n * k)          # assignment a <-> token a // k
    order = jnp.argsort(flat_e)           # stable: groups rows by expert
    token_of = order // k                 # source token per sorted row
    group_sizes = jnp.bincount(flat_e, length=cfg.num_experts)

    inv = jnp.argsort(order)               # inverse permutation: unsort
    x_sorted = _dispatch_gather(
        xf.astype(cfg.dtype), token_of, inv, k
    )                                                            # [NK, H]
    import os

    tile_in = os.environ.get("TPUJOB_RAGGED_TILING_IN")
    tile_out = os.environ.get("TPUJOB_RAGGED_TILING_OUT")
    hmid = _grouped_matmul(x_sorted, experts_in.astype(cfg.dtype),
                           group_sizes, tiling=tile_in)
    hmid = nn.gelu(hmid)
    y_sorted = _grouped_matmul(hmid, experts_out.astype(cfg.dtype),
                               group_sizes, tiling=tile_out)

    # Unsort FIRST, then gate-combine: the gate lives in unsorted (token,
    # slot) order already (topv), so multiplying after the permutation
    # needs no gate gather, and the [N, K, H] multiply + K-sum fuse into
    # one pass instead of materializing a gated [NK, H] copy pre-permute.
    y_unsorted = _permute_rows(y_sorted, inv, order).reshape(n, k, h)
    y = (topv.astype(cfg.dtype)[..., None] * y_unsorted).sum(axis=1)

    aux = {
        # fraction of tokens whose FIRST choice is expert e (Switch f_e)
        "fraction": jax.nn.one_hot(
            topi[:, 0], cfg.num_experts, dtype=jnp.float32
        ).mean(axis=0),
        "prob": probs.mean(axis=0),
        "logits": logits.reshape(b, t, cfg.num_experts),
    }
    return y.reshape(b, t, h), aux


def load_balance_loss(aux: dict, num_experts: int) -> jax.Array:
    """Switch-transformer load-balancing loss: E * sum_e f_e * p_e (== 1.0 at
    perfect uniformity)."""
    return num_experts * (aux["fraction"] * aux["prob"]).sum()


def router_z_loss(aux: dict) -> jax.Array:
    """Penalize large router logits (numerical stability, ST-MoE eq. 5)."""
    z = jax.nn.logsumexp(aux["logits"].astype(jnp.float32), axis=-1)
    return (z**2).mean()


class MoEMlp(nn.Module):
    """Expert-parallel FFN. Expert weights are stacked [E, ...] params sharded
    over `ep`; dispatch/combine are einsums so the tokens<->experts shuffle is
    an XLA all-to-all, not host code."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, t, h = x.shape
        capacity = cfg.capacity(t)

        w_router = self.param(
            "router", nn.initializers.lecun_normal(), (h, cfg.num_experts),
            jnp.float32,
        )
        # batch_axis=0 excludes the stacked expert dim from fan-in: each
        # expert must init like a standalone [h, ffn] dense layer, not with
        # variance shrunk by E.
        expert_init = nn.initializers.lecun_normal(batch_axis=0)
        experts_in = self.param(
            "experts_in", expert_init,
            (cfg.num_experts, h, cfg.ffn), jnp.float32,
        )
        experts_out = self.param(
            "experts_out", expert_init,
            (cfg.num_experts, cfg.ffn, h), jnp.float32,
        )

        if cfg.dispatch == "sparse":
            y, aux = sparse_moe_ffn(x, w_router, experts_in, experts_out, cfg)
            self.sow("moe_losses", "balance",
                     load_balance_loss(aux, cfg.num_experts))
            self.sow("moe_losses", "zloss", router_z_loss(aux))
            return y

        # Router math in f32 (bf16 softmax over experts is too coarse);
        # w_router is up-cast too — under master_weights the live param is
        # a bf16 compute copy.
        logits = jnp.einsum("bth,he->bte", x.astype(jnp.float32),
                            w_router.astype(jnp.float32))
        combine, dispatch, aux = topk_routing(logits, cfg.top_k, capacity)

        self.sow("moe_losses", "balance",
                 load_balance_loss(aux, cfg.num_experts))
        self.sow("moe_losses", "zloss", router_z_loss(aux))

        # Dispatch: [B,T,E,C] x [B,T,H] -> [E,B,C,H]; with batch dp-sharded
        # and experts ep-sharded, XLA lowers this to the ep all-to-all.
        expert_in = jnp.einsum(
            "btec,bth->ebch", dispatch.astype(cfg.dtype), x.astype(cfg.dtype)
        )
        hmid = jnp.einsum(
            "ebch,ehf->ebcf", expert_in, experts_in.astype(cfg.dtype)
        )
        hmid = nn.gelu(hmid)
        expert_out = jnp.einsum(
            "ebcf,efh->ebch", hmid, experts_out.astype(cfg.dtype)
        )
        # Combine back (weighted by gates); dropped tokens (over capacity)
        # contribute 0 — the residual connection carries them through.
        return jnp.einsum(
            "btec,ebch->bth", combine.astype(cfg.dtype), expert_out
        )


class MoEBlock(nn.Module):
    cfg: MoEConfig
    use_moe: bool
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.cfg
        from tf_operator_tpu.models.transformer import TransformerConfig

        attn_cfg = TransformerConfig(
            vocab_size=cfg.vocab_size, num_layers=cfg.num_layers,
            hidden=cfg.hidden, num_heads=cfg.num_heads, max_len=cfg.max_len,
            causal=cfg.causal, dtype=cfg.dtype,
        )
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        x = x + SelfAttention(attn_cfg, self.attn_fn, name="attn")(
            ln("ln1")(x), deterministic
        )
        h = ln("ln2")(x)
        if self.use_moe:
            h = MoEMlp(cfg, name="moe")(h)
        else:
            h = nn.Dense(cfg.ffn, dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="mlp_in")(h)
            h = nn.gelu(h)
            h = nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="mlp_out")(h)
        return x + h


class MoETransformerLM(nn.Module):
    """Causal LM with MoE FFNs every `moe_every` blocks (Mixtral/Switch
    layout: interleaved dense + expert layers).

    setup() (not @nn.compact) so `hidden` can expose the trunk output
    without the head, same pattern as TransformerLM: the full
    [B, T, vocab] f32 logits tensor is the single biggest HBM tensor of a
    step, and the chunked loss computes head+softmax per sequence chunk
    instead. Explicit name= keeps every param path identical to the old
    @nn.compact layout (embed/pos_embed/layer_i/ln_f/lm_head)."""

    cfg: MoEConfig
    attn_fn: AttnFn | None = None

    def setup(self):
        cfg = self.cfg
        self.embed = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="embed")
        self.pos_embed = nn.Embed(cfg.max_len, cfg.hidden, dtype=cfg.dtype,
                                  param_dtype=jnp.float32, name="pos_embed")
        self.blocks = [
            MoEBlock(cfg, (i % cfg.moe_every) == (cfg.moe_every - 1),
                     self.attn_fn, name=f"layer_{i}")
            for i in range(cfg.num_layers)
        ]
        self.ln_f = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                                 name="ln_f")
        self.lm_head = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                                param_dtype=jnp.float32, use_bias=False,
                                name="lm_head")

    def hidden(self, tokens, deterministic=True):
        """Trunk output [B, T, H] (post final LayerNorm), no head."""
        x = self.embed(tokens)
        x = x + self.pos_embed(jnp.arange(tokens.shape[1]))[None]
        for block in self.blocks:
            x = block(x, deterministic)
        return self.ln_f(x)

    def __call__(self, tokens, deterministic=True):
        logits = self.lm_head(self.hidden(tokens, deterministic))
        return logits.astype(jnp.float32)


def moe_lm_loss(
    model: MoETransformerLM, params, tokens: jax.Array,
    chunked: bool = False, chunk: int = 2048,
) -> jax.Array:
    """Next-token loss + the sown MoE aux losses (balance + z-loss).

    chunked=True computes head+softmax per `chunk`-token sequence slice
    (transformer.lm_loss_chunked) instead of materializing [B, T, vocab]
    f32 logits — numerics identical, and the loss fusions ride the scan
    instead of three full-logits HBM round-trips."""
    from tf_operator_tpu.models.transformer import lm_loss, lm_loss_chunked

    cfg = model.cfg
    if chunked:
        h, mut = model.apply(
            {"params": params}, tokens, mutable=["moe_losses"],
            method="hidden",
        )
        loss = lm_loss_chunked(
            h, params["lm_head"]["kernel"], tokens, chunk=chunk
        )
    else:
        logits, mut = model.apply(
            {"params": params}, tokens, mutable=["moe_losses"]
        )
        loss = lm_loss(logits, tokens)
    flat, _ = jax.tree_util.tree_flatten_with_path(mut.get("moe_losses", {}))
    balance = [leaf for path, leaf in flat if "balance" in str(path)]
    zloss = [leaf for path, leaf in flat if "zloss" in str(path)]
    if balance:
        loss = loss + cfg.balance_coef * sum(balance) / len(balance)
    if zloss:
        loss = loss + cfg.zloss_coef * sum(zloss) / len(zloss)
    return loss


def moe_reference_forward(
    params: dict, cfg: MoEConfig, x: jax.Array
) -> jax.Array:
    """Per-token loop reference for MoEMlp (test oracle, no capacity limit):
    y[t] = sum over the top-k experts of gate * FFN_e(x[t])."""
    w_router = params["router"]
    wi, wo = params["experts_in"], params["experts_out"]
    logits = x.astype(jnp.float32) @ w_router
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.top_k)
    if cfg.top_k > 1:  # top-1 keeps the raw softmax prob (Switch eq. 2)
        topv = topv / topv.sum(-1, keepdims=True)
    out = jnp.zeros_like(x, dtype=jnp.float32)
    for k in range(cfg.top_k):
        e = topi[..., k]  # [B, T]
        gate = topv[..., k]
        h = jnp.einsum("bth,bthf->btf", x.astype(jnp.float32), wi[e])
        h = nn.gelu(h)
        y = jnp.einsum("btf,btfh->bth", h, wo[e])
        out = out + gate[..., None] * y
    return out
