"""JAX/flax model zoo for the baseline workloads (SURVEY.md §6):

  mnist        MLP + ConvNet        (dist-mnist / mnist_with_summaries parity)
  resnet       ResNet-50 family     (MultiWorkerMirrored ResNet-50 parity)
  transformer  BERT-base encoder +
               causal LM w/ ring attention (Chief+Worker+Evaluator BERT parity,
                                            long-context first-class)
  moe          Mixture-of-Experts LM, expert-parallel over the `ep` mesh axis
               (GShard dense dispatch; SURVEY.md §2 parallelism table EP row)

All models compute in bfloat16 by default (MXU-native) with f32 params, and
take an injectable attention function so sequence parallelism composes.
"""
