"""MNIST models — parity with the reference's canonical example workloads
(examples/v1/dist-mnist, examples/v1/mnist_with_summaries)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    """The dist-mnist example's 784-500-10 shape."""

    hidden: int = 500
    classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class ConvNet(nn.Module):
    """The mnist_with_summaries-style small CNN."""

    classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]
        x = nn.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean(jnp.argmax(logits, -1) == labels)
