"""Checkpoint save/restore + the trainer->evaluator handoff protocol.

The reference operator never managed checkpoints itself: users mounted PVs
and TensorFlow checkpointed; the evaluator replica followed the checkpoint
stream (SURVEY.md §5 "Checkpoint / resume", §2 Evaluator row). Same contract
here, TPU-native: the chief (or worker-0) writes orbax checkpoints under
--checkpoint-dir, the Evaluator replica polls the directory, restores each
new step and evaluates. A FINAL marker file tells the evaluator the stream
is complete so it can exit cleanly.

Layout:  <dir>/step_<N>/...   (orbax PyTree checkpoint, atomic rename)
         <dir>/FINAL          (text: last step number)

Publish discipline (round 15, zero-stall checkpointing): save_named
writes through a `<name>.orbax-checkpoint-tmp-publish` staging dir and
publishes with one rename, so the async write leg (models/train.py's
ckpt-writer thread) can be killed at ANY point — including held open by
`stall:ckpt=N` chaos — leaving only tmp entries sweep_tmp_dirs removes
at startup. Multi-process runtimes get PROCESS-LOCAL checkpointers
(every orbax barrier scoped to the calling process, over the
jax.distributed gRPC client): the trees saved here are host snapshots of
fully-replicated leaves, so process 0 writes alone and a gang member's
death can never wedge a peer's save mid-barrier.

Dtype contract (mixed-precision optimizer state, tf_operator_tpu/optim.py):
trees save at their in-memory dtypes (bf16 Adam moments persist as bf16,
the f32 master copy as f32 — a bf16-moment checkpoint is ~half the f32
one's optimizer payload), and restore CASTS to the template's dtypes (a
host-side cast in restore_named — see its docstring for why the orbax
RestoreArgs path is avoided), so a legacy all-f32 trainstate loads under a
bf16-moment config and vice versa. A template whose LEAF LIST doesn't
match the saved tree (e.g. a trainstate written without master weights
restored under a master-weights config) raises ValueError from the arity
check; models/train._try_resume catches that and falls back to a
params-only resume. Both behaviors are pinned by tests/test_optimizer.py.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import Any

from tf_operator_tpu import telemetry

_STEP_RE = re.compile(r"^step_(\d+)$")

# Sibling manifest, written AFTER the orbax save completes: a file census
# ({relative path: byte size}) of the finished checkpoint. Its presence
# means "the save ran to completion"; a size/membership mismatch means a
# torn write (truncated metadata, lost leaf dir) — the resume walk skips
# such steps instead of crash-looping on them. It lives BESIDE the orbax
# dir (never inside: orbax owns that layout), and the name can't collide
# with list_steps' `^step_<N>$` directory match.
MANIFEST_SUFFIX = ".manifest.json"

# Second sibling, the SHARDING manifest (topology-portable checkpoints):
# the gang shape the checkpoint was saved from — process/device count,
# mesh axis layout, per-leaf PartitionSpec + global shape/dtype, and a
# crc32 digest of the host bytes. Restore reads it to decide same-shape
# vs reshard (a target mesh that differs re-lays-out every leaf via
# shard-by-spec device_put), to check global-shape equality before a
# reshard, and to prove bit-equality of what came back. A checkpoint
# WITHOUT one (pre-manifest / hand-written) gets the same grace as a
# missing size census: restorable, but same-shape semantics only.
SHARDING_SUFFIX = ".sharding.json"


# Publish discipline: every save lands under a tmp name carrying orbax's
# own tmp marker, then renames to the final name. A kill mid-write (or
# mid-stall, under `stall:ckpt=N` chaos) strands only this tmp dir —
# which sweep_tmp_dirs already removes at startup and list_steps'
# `^step_<N>$` match never sees — so the async write leg can die at ANY
# point without presenting a torn checkpoint to the resume walk.
TMP_PUBLISH_MARKER = ".orbax-checkpoint-tmp"


# One Checkpointer per process, built lazily: constructing one per save
# costs a metadata-store + handler setup comparable to a small tree's
# whole write (measured ~half the mnist save), which the async writer
# would pay on every periodic save. The instance is USED by exactly one
# thread at a time (the writer pipeline admits one in-flight save; sync
# saves and restores happen on the main thread while no write is in
# flight) — but first-touch can race (the writer thread's warm-up vs the
# main thread's resume restore), hence the construction lock.
_CHECKPOINTER = None
_CHECKPOINTER_LOCK = threading.Lock()


def process_local_io() -> bool:
    """Whether this runtime supports PROCESS-LOCAL checkpoint IO (the
    round-15 model: every orbax barrier scoped to the calling process,
    process 0 saving alone). True for single-process runtimes and for
    multi-process ones initialized through jax.distributed (whose gRPC
    client carries the scoped barriers). False only for a multi-process
    world WITHOUT a distributed client (e.g. a raw multi-host TPU pod
    that never ran jax.distributed.initialize): there _checkpointer()
    falls back to gang-wide barriers, so EVERY process must enter each
    save (the legacy rule) and the async writer must stand down — those
    barriers dispatch XLA collectives, which a background thread must
    never do."""
    import jax

    if jax.process_count() == 1:
        return True
    from jax._src import distributed

    return distributed.global_state.client is not None


def _checkpointer():
    global _CHECKPOINTER
    if _CHECKPOINTER is not None:
        return _CHECKPOINTER
    with _CHECKPOINTER_LOCK:
        if _CHECKPOINTER is None:
            _CHECKPOINTER = _build_checkpointer()
    return _CHECKPOINTER


def _build_checkpointer():
    import orbax.checkpoint as ocp

    import jax

    if jax.process_count() > 1:
        from jax._src import distributed

        if distributed.global_state.client is not None:
            # Multi-process runtimes get a PROCESS-LOCAL checkpointer:
            # active_processes = {me}, so every barrier orbax takes spans
            # exactly this process (and rides the jax.distributed gRPC
            # client — never multihost_utils.sync_global_devices, an XLA
            # psum a background thread must not dispatch).
            #
            # Why not a gang-wide collective save? Two reasons, both load
            # bearing for the async writer thread (models/train.py):
            #   1. The trees this trainer checkpoints are HOST snapshots
            #      of leaves that are fully replicated across processes
            #      (multi-process jobs shard data axes only — the same
            #      invariant PR 9's reshape support documents), so one
            #      process holds everything worth writing; the gang-wide
            #      barriers orbax would take coordinate work that doesn't
            #      exist here.
            #   2. A collective write leg inherits the gang's failure
            #      domain: one member SIGKILLed mid-save leaves every
            #      peer's writer thread wedged in a barrier waiting for a
            #      dead process — an async save would then block its
            #      job's own preemption drain. Process-local writes keep
            #      a peer's death from touching this process's pipeline.
            # The per-process key prefix keeps the one shared coordination
            # service from ever seeing two same-named barriers with
            # different member sets (e.g. both processes restoring
            # step_N at resume).
            from orbax.checkpoint import options as ocp_options

            me = jax.process_index()
            return ocp.Checkpointer(
                ocp.PyTreeCheckpointHandler(),
                multiprocessing_options=ocp_options.MultiprocessingOptions(
                    primary_host=me,
                    active_processes={me},
                    barrier_sync_key_prefix=f"proc{me}",
                ),
            )
    return ocp.PyTreeCheckpointer()


def _publish_stall(name: str) -> None:
    """Deterministic chaos window between the finished tmp write and the
    publishing rename: `stall:ckpt=N,delay=S` sleeps here while saving
    step N — a `kill:` landing during the sleep leaves exactly one orbax
    tmp dir, the torn-async-write scenario the startup sweep + backward
    resume walk must absorb. Zero-cost when TPUJOB_CHAOS is unset."""
    from tf_operator_tpu import chaos as chaos_lib

    stalls = chaos_lib.ckpt_stalls_from_env()
    if not stalls:
        return
    m = _STEP_RE.match(name)
    if m is None:
        return
    delay = chaos_lib.ckpt_stall_delay(int(m.group(1)), stalls)
    if delay > 0:
        time.sleep(delay)


def _manifest_path(ckpt_dir: str, name: str) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), name + MANIFEST_SUFFIX)


def _file_census(root: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for dirpath, _, filenames in os.walk(root):
        for f in filenames:
            p = os.path.join(dirpath, f)
            out[os.path.relpath(p, root)] = os.path.getsize(p)
    return out


def write_manifest(ckpt_dir: str, name: str) -> str:
    """Census the finished checkpoint <dir>/<name> into its manifest
    (tmp+rename, so a half-written manifest never validates)."""
    root = os.path.join(os.path.abspath(ckpt_dir), name)
    census = _file_census(root)
    path = _manifest_path(ckpt_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"  # unique per writer: replace is atomic
    with open(tmp, "w") as f:
        json.dump({"name": name, "files": census,
                   "total_bytes": sum(census.values())}, f)
    os.replace(tmp, path)
    return path


def _sharding_path(ckpt_dir: str, name: str) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), name + SHARDING_SUFFIX)


def _spec_entry(e):
    """One PartitionSpec entry -> JSON (None | axis name | [axis names])."""
    if e is None:
        return None
    if isinstance(e, (tuple, list)):
        return [str(a) for a in e]
    return str(e)


def leaf_shardings(tree: Any) -> dict[str, dict]:
    """{leaf path: {"spec", "shape", "dtype"}} for a (possibly live,
    device-resident) tree. Leaves without a NamedSharding (host numpy,
    scalars) record spec=None — fully replicated, which is exactly how
    restore would lay them out."""
    import jax

    out: dict[str, dict] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        spec = None
        sharding = getattr(leaf, "sharding", None)
        pspec = getattr(sharding, "spec", None)
        if pspec is not None:
            spec = [_spec_entry(e) for e in pspec]
        out[key] = {
            "spec": spec,
            "shape": [int(d) for d in getattr(leaf, "shape", ())],
            "dtype": str(getattr(leaf, "dtype", "")),
        }
    return out


def tree_digest(tree: Any) -> str:
    """crc32 over every leaf's raw bytes in deterministic (path-sorted)
    order — the cheap bit-equality witness the sharding manifest records
    at save and the `resumed` event reports back after restore. Computed
    on HOST arrays (call after device_get)."""
    import zlib

    import jax
    import numpy as np

    leaves = sorted(
        (jax.tree_util.keystr(p), leaf)
        for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    )
    crc = 0
    for key, leaf in leaves:
        crc = zlib.crc32(key.encode(), crc)
        arr = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(arr.tobytes(), crc)
    return f"{crc:08x}"


def write_sharding_manifest(ckpt_dir: str, name: str, info: dict) -> str:
    """Persist the sharding manifest beside <dir>/<name> (tmp+rename,
    same atomicity discipline as the size census)."""
    path = _sharding_path(ckpt_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)
    return path


def read_sharding_manifest(ckpt_dir: str, name: str) -> dict | None:
    """The sharding manifest of <dir>/<name>, or None when absent OR torn
    — a checkpoint whose shape cannot be verified degrades to same-shape-
    only restore semantics, it never crashes the resume walk."""
    try:
        with open(_sharding_path(ckpt_dir, name)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    return manifest if isinstance(manifest, dict) else None


def validate_named(ckpt_dir: str, name: str) -> bool:
    """Is <dir>/<name> a complete checkpoint? With a manifest: every
    censused file must exist at its recorded size (a torn/truncated or
    missing file fails). Without one (legacy/external checkpoints that
    predate manifests): optimistically True — the resume walk's
    restore-with-fallback still catches an unreadable tree."""
    root = os.path.join(os.path.abspath(ckpt_dir), name)
    if not os.path.isdir(root):
        return False
    mpath = _manifest_path(ckpt_dir, name)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except FileNotFoundError:
        return True  # pre-manifest checkpoint: unverifiable, not invalid
    except (OSError, ValueError, KeyError, TypeError):
        return False  # torn manifest: the save did not finish cleanly
    for rel, size in files.items():
        p = os.path.join(root, rel)
        try:
            if os.path.getsize(p) != int(size):
                return False
        except OSError:
            return False
    return True


def validate_step(ckpt_dir: str, step: int) -> bool:
    return validate_named(ckpt_dir, f"step_{step}")


def save_named(ckpt_dir: str, name: str, tree: Any) -> str:
    """Atomically persist `tree` under <dir>/<name>; returns the path.

    Two-phase publish: orbax writes the full tree under a tmp name
    (<name>.orbax-checkpoint-tmp-publish, identical on every process —
    see the barrier-key note below; orbax's own internal tmp+rename runs
    inside that), then ONE rename publishes the final name and the
    census manifest follows. A death at any point before the rename —
    including the async write leg SIGKILLed mid-write, or held in the
    `stall:ckpt=N` chaos window — leaves only tmp entries the startup
    sweep removes; readers (resume walk, evaluator poll) never observe a
    partially-written final name."""
    root = os.path.abspath(ckpt_dir)
    path = os.path.join(root, name)
    # The tmp name must be IDENTICAL on every process: orbax's multihost
    # barrier keys embed the directory name, so a per-pid suffix would
    # give each gang member a different barrier and deadlock the save.
    # Uniqueness across concurrent saves of the same name is not needed —
    # the writer pipeline admits one in-flight save, and a stale tmp from
    # a killed generation is replaced by force=True (and swept at start).
    tmp = os.path.join(root, f"{name}{TMP_PUBLISH_MARKER}-publish")
    # Checkpoint IO is the canonical p99 step stall; the span makes a save
    # that blocked the step loop visible on the --trace timeline (on the
    # async path it rides the writer thread's timeline instead).
    with telemetry.span("checkpoint/save", ckpt=name):
        _checkpointer().save(tmp, tree, force=True)
        # Publish + manifest from process 0 only (orbax writes from
        # process 0 too, and its save barrier has completed by here, so
        # every process' data is on disk before the rename).
        import jax

        if jax.process_index() == 0:
            _publish_stall(name)
            if os.path.isdir(path):
                # Re-save of an existing name (a resumed generation
                # re-reaching a saved step): same replace semantics as
                # orbax force=True, applied at the publish boundary.
                shutil.rmtree(path)
            os.rename(tmp, path)
            write_manifest(ckpt_dir, name)
    return path


def restore_named(ckpt_dir: str, name: str, template: Any | None = None) -> Any:
    """Restore <dir>/<name>. With a template, leaves come back at the
    TEMPLATE's dtypes (the mixed-precision dtype contract in the module
    docstring); without one, at their saved dtypes. Raises
    FileNotFoundError when absent, ValueError when the template's tree
    doesn't match the saved one.

    The restore deliberately does NOT go through orbax's
    construct_restore_args/RestoreArgs path: on this orbax/tensorstore
    build, a restore_args-driven read of the trainer's aux tree (0-d step
    scalar + flat opt-leaf list) corrupts the glibc heap — a later
    unrelated malloc then aborts with 'corrupted double-linked list'
    (reproduced: resume-restore, then jitted train steps, then any orbax
    save). Restoring the raw saved tree and casting to the template's
    dtypes host-side is equivalent for the numpy trees this repo
    checkpoints, and sidesteps the crash; the tree-structure mismatch
    still raises ValueError (from jax.tree.map arity checking), which
    _try_resume's params-only fallback relies on."""
    path = os.path.join(os.path.abspath(ckpt_dir), name)
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    with telemetry.span("checkpoint/restore", ckpt=name):
        restored = _checkpointer().restore(path)
    if template is None:
        return restored
    import jax
    import numpy as np

    def cast(raw, tmpl):
        if hasattr(tmpl, "dtype"):
            # ALWAYS copy (astype's default), even on dtype match: a
            # copy=False cast hands out aliases of orbax/tensorstore-owned
            # buffers, and an alias that later reaches XLA (donated train
            # state) reproduces the heap-corruption abort this module
            # exists to avoid. The transient second tree on the common
            # same-dtype resume is host RAM, bounded by the checkpoint
            # size — the safe trade.
            return np.asarray(raw).astype(tmpl.dtype)
        return raw

    return jax.tree.map(cast, restored, template)


def save(ckpt_dir: str, step: int, tree: Any) -> str:
    """Atomically persist `tree` as step `step`; returns the checkpoint path."""
    return save_named(ckpt_dir, f"step_{step}", tree)


def restore(ckpt_dir: str, step: int, template: Any | None = None) -> Any:
    return restore_named(ckpt_dir, f"step_{step}", template)


def list_steps(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        # Orbax writes to a tmp dir then renames: only finished checkpoints
        # carry the final name and a metadata file.
        if m and os.path.isdir(os.path.join(ckpt_dir, name)):
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(ckpt_dir: str) -> int | None:
    steps = list_steps(ckpt_dir)
    return steps[-1] if steps else None


def latest_valid_checkpoint(
    ckpt_dir: str, template_shapes: dict[str, list[int]] | None = None,
) -> int | None:
    """The newest step that passes the resume walk's VALIDATION — the
    public consumer surface (serving restore, tooling) of the trainer's
    backward walk, so no consumer can ever load a checkpoint the trainer
    itself would skip (a raw `latest_step` can name a torn save).

    Walks list_steps newest-first:
      * a step whose census manifest fails validate_step (torn write,
        truncated leaf, missing file) is skipped — exactly the trainer's
        `invalid_checkpoint` fallback;
      * with `template_shapes` ({leaf path: global shape}, the shape of
        the model the caller intends to apply), a step whose sharding
        manifest records DIFFERENT per-leaf global shapes is skipped —
        the trainer's `reshard_shape_mismatch` gate (a model-config
        change, not a restorable candidate). Steps with no sharding
        manifest (pre-manifest/hand-written) get the same grace as in
        the resume walk: unverifiable, not invalid.

    Foreign GANG shapes (different process count/mesh) are deliberately
    NOT skipped: the trees this repo checkpoints are host snapshots of
    fully-replicated leaves, so a single-process consumer restores them
    regardless of the saving gang's shape (the same property PR 9's
    reshard path relies on). Returns None when nothing validates."""
    for s in reversed(list_steps(ckpt_dir)):
        if not validate_step(ckpt_dir, s):
            continue
        if template_shapes is not None:
            sm = read_sharding_manifest(ckpt_dir, f"step_{s}")
            if sm is not None and sm.get("leaves"):
                saved = {k: v.get("shape")
                         for k, v in sm["leaves"].items()}
                if saved != template_shapes:
                    continue
        return s
    return None


def mark_final(ckpt_dir: str, step: int) -> None:
    tmp = os.path.join(ckpt_dir, ".FINAL.tmp")
    with open(tmp, "w") as f:
        f.write(str(step))
    os.replace(tmp, os.path.join(ckpt_dir, "FINAL"))


def final_step(ckpt_dir: str) -> int | None:
    path = os.path.join(ckpt_dir, "FINAL")
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def prune_checkpoints(ckpt_dir: str, keep: int) -> list[int]:
    """Retention: delete all but the newest `keep` step checkpoints
    (each step's params dir, its trainstate aux dir, and both manifests).
    Returns the pruned step numbers. keep < 1 keeps everything — the
    historical unbounded behavior stays opt-in-able."""
    if keep < 1:
        return []
    steps = list_steps(ckpt_dir)
    pruned: list[int] = []
    root = os.path.abspath(ckpt_dir)
    for s in steps[:-keep]:
        for name in (f"step_{s}", f"trainstate_{s}"):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            for mpath in (_manifest_path(ckpt_dir, name),
                          _sharding_path(ckpt_dir, name)):
                try:
                    os.unlink(mpath)
                except OSError:
                    pass
        pruned.append(s)
    return pruned


def sweep_tmp_dirs(ckpt_dir: str) -> list[str]:
    """Startup sweep of write leftovers a kill can strand: orbax's
    `*.orbax-checkpoint-tmp-*` staging dirs (a preempted save that never
    reached its rename), our manifest `.tmp*` files, and `.FINAL.tmp`.
    Never touches finished checkpoints (final names carry none of these
    markers). Returns the removed entry names."""
    if not os.path.isdir(ckpt_dir):
        return []
    removed: list[str] = []
    for name in os.listdir(ckpt_dir):
        path = os.path.join(ckpt_dir, name)
        is_tmp = (
            ".orbax-checkpoint-tmp" in name
            or name == ".FINAL.tmp"
            or (MANIFEST_SUFFIX + ".tmp") in name
            or (SHARDING_SUFFIX + ".tmp") in name
        )
        if not is_tmp:
            continue
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
            removed.append(name)
        except OSError:
            continue  # best-effort: a sweep must never fail a startup
    return removed


def wait_for_new_step(
    ckpt_dir: str, seen: set[int], timeout: float, poll: float = 0.2,
    should_stop=None,
) -> int | None:
    """Block until a checkpoint not in `seen` appears; None on timeout,
    when the FINAL marker is set and every step has been consumed, or when
    `should_stop()` turns true (the evaluator's preemption latch — a
    SIGTERM must not sit out the full eval timeout)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if should_stop is not None and should_stop():
            return None
        for s in list_steps(ckpt_dir):
            if s not in seen:
                return s
        fs = final_step(ckpt_dir)
        if fs is not None and fs in seen:
            return None  # stream complete
        time.sleep(poll)
    return None
