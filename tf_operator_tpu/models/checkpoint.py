"""Checkpoint save/restore + the trainer->evaluator handoff protocol.

The reference operator never managed checkpoints itself: users mounted PVs
and TensorFlow checkpointed; the evaluator replica followed the checkpoint
stream (SURVEY.md §5 "Checkpoint / resume", §2 Evaluator row). Same contract
here, TPU-native: the chief (or worker-0) writes orbax checkpoints under
--checkpoint-dir, the Evaluator replica polls the directory, restores each
new step and evaluates. A FINAL marker file tells the evaluator the stream
is complete so it can exit cleanly.

Layout:  <dir>/step_<N>/...   (orbax PyTree checkpoint, atomic rename)
         <dir>/FINAL          (text: last step number)

Dtype contract (mixed-precision optimizer state, tf_operator_tpu/optim.py):
trees save at their in-memory dtypes (bf16 Adam moments persist as bf16,
the f32 master copy as f32 — a bf16-moment checkpoint is ~half the f32
one's optimizer payload), and restore CASTS to the template's dtypes (a
host-side cast in restore_named — see its docstring for why the orbax
RestoreArgs path is avoided), so a legacy all-f32 trainstate loads under a
bf16-moment config and vice versa. A template whose LEAF LIST doesn't
match the saved tree (e.g. a trainstate written without master weights
restored under a master-weights config) raises ValueError from the arity
check; models/train._try_resume catches that and falls back to a
params-only resume. Both behaviors are pinned by tests/test_optimizer.py.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Any

from tf_operator_tpu import telemetry

_STEP_RE = re.compile(r"^step_(\d+)$")

# Sibling manifest, written AFTER the orbax save completes: a file census
# ({relative path: byte size}) of the finished checkpoint. Its presence
# means "the save ran to completion"; a size/membership mismatch means a
# torn write (truncated metadata, lost leaf dir) — the resume walk skips
# such steps instead of crash-looping on them. It lives BESIDE the orbax
# dir (never inside: orbax owns that layout), and the name can't collide
# with list_steps' `^step_<N>$` directory match.
MANIFEST_SUFFIX = ".manifest.json"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _manifest_path(ckpt_dir: str, name: str) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), name + MANIFEST_SUFFIX)


def _file_census(root: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for dirpath, _, filenames in os.walk(root):
        for f in filenames:
            p = os.path.join(dirpath, f)
            out[os.path.relpath(p, root)] = os.path.getsize(p)
    return out


def write_manifest(ckpt_dir: str, name: str) -> str:
    """Census the finished checkpoint <dir>/<name> into its manifest
    (tmp+rename, so a half-written manifest never validates)."""
    root = os.path.join(os.path.abspath(ckpt_dir), name)
    census = _file_census(root)
    path = _manifest_path(ckpt_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"  # unique per writer: replace is atomic
    with open(tmp, "w") as f:
        json.dump({"name": name, "files": census,
                   "total_bytes": sum(census.values())}, f)
    os.replace(tmp, path)
    return path


def validate_named(ckpt_dir: str, name: str) -> bool:
    """Is <dir>/<name> a complete checkpoint? With a manifest: every
    censused file must exist at its recorded size (a torn/truncated or
    missing file fails). Without one (legacy/external checkpoints that
    predate manifests): optimistically True — the resume walk's
    restore-with-fallback still catches an unreadable tree."""
    root = os.path.join(os.path.abspath(ckpt_dir), name)
    if not os.path.isdir(root):
        return False
    mpath = _manifest_path(ckpt_dir, name)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except FileNotFoundError:
        return True  # pre-manifest checkpoint: unverifiable, not invalid
    except (OSError, ValueError, KeyError, TypeError):
        return False  # torn manifest: the save did not finish cleanly
    for rel, size in files.items():
        p = os.path.join(root, rel)
        try:
            if os.path.getsize(p) != int(size):
                return False
        except OSError:
            return False
    return True


def validate_step(ckpt_dir: str, step: int) -> bool:
    return validate_named(ckpt_dir, f"step_{step}")


def save_named(ckpt_dir: str, name: str, tree: Any) -> str:
    """Atomically persist `tree` under <dir>/<name>; returns the path."""
    path = os.path.join(os.path.abspath(ckpt_dir), name)
    # Checkpoint IO is the canonical p99 step stall; the span makes a save
    # that blocked the step loop visible on the --trace timeline.
    with telemetry.span("checkpoint/save", ckpt=name):
        _checkpointer().save(path, tree, force=True)
        # Manifest from process 0 only (orbax writes from process 0 too;
        # per-writer tmp names keep even a misconfigured double-writer
        # safe, since os.replace is atomic).
        import jax

        if jax.process_index() == 0:
            write_manifest(ckpt_dir, name)
    return path


def restore_named(ckpt_dir: str, name: str, template: Any | None = None) -> Any:
    """Restore <dir>/<name>. With a template, leaves come back at the
    TEMPLATE's dtypes (the mixed-precision dtype contract in the module
    docstring); without one, at their saved dtypes. Raises
    FileNotFoundError when absent, ValueError when the template's tree
    doesn't match the saved one.

    The restore deliberately does NOT go through orbax's
    construct_restore_args/RestoreArgs path: on this orbax/tensorstore
    build, a restore_args-driven read of the trainer's aux tree (0-d step
    scalar + flat opt-leaf list) corrupts the glibc heap — a later
    unrelated malloc then aborts with 'corrupted double-linked list'
    (reproduced: resume-restore, then jitted train steps, then any orbax
    save). Restoring the raw saved tree and casting to the template's
    dtypes host-side is equivalent for the numpy trees this repo
    checkpoints, and sidesteps the crash; the tree-structure mismatch
    still raises ValueError (from jax.tree.map arity checking), which
    _try_resume's params-only fallback relies on."""
    path = os.path.join(os.path.abspath(ckpt_dir), name)
    if not os.path.isdir(path):
        raise FileNotFoundError(path)
    with telemetry.span("checkpoint/restore", ckpt=name):
        restored = _checkpointer().restore(path)
    if template is None:
        return restored
    import jax
    import numpy as np

    def cast(raw, tmpl):
        if hasattr(tmpl, "dtype"):
            # ALWAYS copy (astype's default), even on dtype match: a
            # copy=False cast hands out aliases of orbax/tensorstore-owned
            # buffers, and an alias that later reaches XLA (donated train
            # state) reproduces the heap-corruption abort this module
            # exists to avoid. The transient second tree on the common
            # same-dtype resume is host RAM, bounded by the checkpoint
            # size — the safe trade.
            return np.asarray(raw).astype(tmpl.dtype)
        return raw

    return jax.tree.map(cast, restored, template)


def save(ckpt_dir: str, step: int, tree: Any) -> str:
    """Atomically persist `tree` as step `step`; returns the checkpoint path."""
    return save_named(ckpt_dir, f"step_{step}", tree)


def restore(ckpt_dir: str, step: int, template: Any | None = None) -> Any:
    return restore_named(ckpt_dir, f"step_{step}", template)


def list_steps(ckpt_dir: str) -> list[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        # Orbax writes to a tmp dir then renames: only finished checkpoints
        # carry the final name and a metadata file.
        if m and os.path.isdir(os.path.join(ckpt_dir, name)):
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(ckpt_dir: str) -> int | None:
    steps = list_steps(ckpt_dir)
    return steps[-1] if steps else None


def mark_final(ckpt_dir: str, step: int) -> None:
    tmp = os.path.join(ckpt_dir, ".FINAL.tmp")
    with open(tmp, "w") as f:
        f.write(str(step))
    os.replace(tmp, os.path.join(ckpt_dir, "FINAL"))


def final_step(ckpt_dir: str) -> int | None:
    path = os.path.join(ckpt_dir, "FINAL")
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def prune_checkpoints(ckpt_dir: str, keep: int) -> list[int]:
    """Retention: delete all but the newest `keep` step checkpoints
    (each step's params dir, its trainstate aux dir, and both manifests).
    Returns the pruned step numbers. keep < 1 keeps everything — the
    historical unbounded behavior stays opt-in-able."""
    if keep < 1:
        return []
    steps = list_steps(ckpt_dir)
    pruned: list[int] = []
    root = os.path.abspath(ckpt_dir)
    for s in steps[:-keep]:
        for name in (f"step_{s}", f"trainstate_{s}"):
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)
            try:
                os.unlink(_manifest_path(ckpt_dir, name))
            except OSError:
                pass
        pruned.append(s)
    return pruned


def sweep_tmp_dirs(ckpt_dir: str) -> list[str]:
    """Startup sweep of write leftovers a kill can strand: orbax's
    `*.orbax-checkpoint-tmp-*` staging dirs (a preempted save that never
    reached its rename), our manifest `.tmp*` files, and `.FINAL.tmp`.
    Never touches finished checkpoints (final names carry none of these
    markers). Returns the removed entry names."""
    if not os.path.isdir(ckpt_dir):
        return []
    removed: list[str] = []
    for name in os.listdir(ckpt_dir):
        path = os.path.join(ckpt_dir, name)
        is_tmp = (
            ".orbax-checkpoint-tmp" in name
            or name == ".FINAL.tmp"
            or (MANIFEST_SUFFIX + ".tmp") in name
        )
        if not is_tmp:
            continue
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
            removed.append(name)
        except OSError:
            continue  # best-effort: a sweep must never fail a startup
    return removed


def wait_for_new_step(
    ckpt_dir: str, seen: set[int], timeout: float, poll: float = 0.2,
    should_stop=None,
) -> int | None:
    """Block until a checkpoint not in `seen` appears; None on timeout,
    when the FINAL marker is set and every step has been consumed, or when
    `should_stop()` turns true (the evaluator's preemption latch — a
    SIGTERM must not sit out the full eval timeout)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if should_stop is not None and should_stop():
            return None
        for s in list_steps(ckpt_dir):
            if s not in seen:
                return s
        fs = final_step(ckpt_dir)
        if fs is not None and fs in seen:
            return None  # stream complete
        time.sleep(poll)
    return None
