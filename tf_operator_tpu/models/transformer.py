"""Transformer family: BERT-style encoder and causal LM, TPU-first.

Parity target: BASELINE.md workload 4 (Chief+Worker+Evaluator BERT-base) —
plus the long-context capability the reference lacked: the attention function
is injectable, so the same module runs single-device reference attention or
ring attention over the `sp` mesh axis (parallel/ring_attention.py).

Module names are the contract for the tensor-parallel sharding rules
(parallel/sharding_rules.TRANSFORMER_TP_RULES): query/key/value, attn_out,
mlp_in, mlp_out, embed, lm_head.

TPU notes: bf16 compute / f32 params; head_dim kept >=128-friendly shapes;
no dropout by default (bench determinism) but supported via `dropout_rate`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp

from tf_operator_tpu.parallel.ring_attention import attention_reference


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522  # BERT-base vocabulary
    num_layers: int = 12
    hidden: int = 768
    num_heads: int = 12
    mlp_ratio: int = 4
    max_len: int = 512
    causal: bool = False
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.bfloat16
    # Per-LAYER rematerialization: save only each block's input for the
    # backward and recompute the block's internals (qkv, mlp, attention
    # residuals). At seq 64k x 12L x 768h the saved intermediates alone are
    # ~17 GB > the 15.75 GB chip — layer remat is what makes 64k trainable
    # on one v5e (~1.2 GB of layer inputs instead). ~33% more FLOPs on the
    # backward; the loss-level remat (--remat) composes with it.
    remat_layers: bool = False
    # With remat_layers, ALSO save the flash kernel's (o, lse) residuals
    # (checkpoint_name tags in ops/flash_attention._fwd_rule): the backward
    # then replays only the linear ops (qkv/mlp/ln) and never re-runs the
    # O(T^2) flash forward — ~25% less backward device work at long seq.
    # Fits the 64k x 12L x 768h single-chip bench point since round 5's
    # chunked-CE fix (the apparent 15.6 G floor was mostly the loss scan's
    # stacked logits residuals) and IS that point's bench config
    # (0.59 MFU). At 128k the +200 MB/layer o tensors OOM past 9 layers —
    # use remat_save_flash_layers there. Sharded sp jobs benefit even
    # more (per-device o is T/n-sized).
    remat_save_flash: bool = False
    # Middle ground (VERDICT r4 #4): save the flash residuals for only the
    # FIRST K layers (0 = none unless remat_save_flash, which saves all).
    # Each saved layer costs one [B, T, H] bf16 o (+[B, heads, T] f32 lse)
    # of HBM and removes that layer's O(T^2) kernel replay from the
    # backward — so K dials memory->speed in ~100 MB steps at the 64k
    # bench point, where all-12 OOMs but a subset may fit.
    remat_save_flash_layers: int = 0

    def __post_init__(self):
        # Same invariants models/train.py enforces at the CLI (ap.error),
        # so non-CLI callers (bench harnesses, notebooks, dryruns) get the
        # signal at CONFIG CONSTRUCTION instead of a silently vacuous
        # save-flash policy: the flags select which residuals per-layer
        # remat keeps, so without remat_layers they do nothing.
        if ((self.remat_save_flash or self.remat_save_flash_layers)
                and not self.remat_layers):
            raise ValueError(
                "remat_save_flash[_layers] requires remat_layers=True (they "
                "select WHICH residuals per-layer remat keeps; without "
                "remat_layers the policy never applies)"
            )
        if self.remat_save_flash and self.remat_save_flash_layers:
            raise ValueError(
                "remat_save_flash (all layers) conflicts with "
                "remat_save_flash_layers (a subset): pick one — all-layers "
                "would silently win and can OOM exactly where the K dial "
                "was chosen to fit"
            )
        if self.remat_save_flash_layers < 0:
            raise ValueError("remat_save_flash_layers must be >= 0")

    @property
    def head_dim(self) -> int:
        return self.hidden // self.num_heads


BERT_BASE = TransformerConfig()
BERT_LARGE = TransformerConfig(num_layers=24, hidden=1024, num_heads=16)


def _tiny(causal: bool = False, **kw) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=1024, num_layers=2, hidden=128, num_heads=4, max_len=256,
        causal=causal, **kw,
    )


TINY = _tiny()
TINY_LM = _tiny(causal=True)

AttnFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


class SelfAttention(nn.Module):
    cfg: TransformerConfig
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.cfg
        b, t, _ = x.shape
        dense = lambda name: nn.Dense(  # noqa: E731
            cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32, name=name
        )
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)

        def split(a):  # [B, T, H*D] -> [B, H, T, D]
            return a.reshape(b, t, cfg.num_heads, cfg.head_dim).transpose(0, 2, 1, 3)

        attn = self.attn_fn
        if attn is None:
            attn = lambda q, k, v: attention_reference(q, k, v, causal=cfg.causal)  # noqa: E731
        o = attn(split(q), split(k), split(v))
        o = o.transpose(0, 2, 1, 3).reshape(b, t, cfg.hidden)
        return nn.Dense(
            cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32, name="attn_out"
        )(o)


class Block(nn.Module):
    cfg: TransformerConfig
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, x, deterministic=True):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32, name=name)  # noqa: E731
        # Pre-LN: stabler for deep stacks, standard on TPU training.
        h = SelfAttention(cfg, self.attn_fn, name="attn")(ln("ln1")(x), deterministic)
        if cfg.dropout_rate:
            h = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(h)
        x = x + h
        h = nn.Dense(
            cfg.hidden * cfg.mlp_ratio, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="mlp_in",
        )(ln("ln2")(x))
        h = nn.gelu(h)
        h = nn.Dense(
            cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32, name="mlp_out"
        )(h)
        if cfg.dropout_rate:
            h = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)(h)
        return x + h


class Transformer(nn.Module):
    """Token encoder/decoder trunk; returns final hidden states."""

    cfg: TransformerConfig
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, tokens, deterministic=True):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="embed",
        )(tokens)
        pos = nn.Embed(
            cfg.max_len, cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="pos_embed",
        )(jnp.arange(tokens.shape[1]))
        x = x + pos[None]
        if cfg.remat_layers:
            save_policy = jax.checkpoint_policies.save_only_these_names(
                "flash_o", "flash_lse")
            policy = save_policy if cfg.remat_save_flash else None
            block_cls = nn.remat(Block, static_argnums=(2,), policy=policy)
            # Layer-subset save-flash: the first K layers keep their flash
            # residuals (no O(T^2) replay), the rest do full recompute —
            # K * ~[B,T,H] of extra HBM buys K/L of the replay back.
            save_block_cls = (
                nn.remat(Block, static_argnums=(2,), policy=save_policy)
                if cfg.remat_save_flash_layers > 0 else block_cls
            )
        else:
            block_cls = save_block_cls = Block
        for i in range(cfg.num_layers):
            cls = (save_block_cls if i < cfg.remat_save_flash_layers
                   else block_cls)
            x = cls(cfg, self.attn_fn, name=f"layer_{i}")(
                x, deterministic)
        return nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32, name="ln_f")(x)


class TransformerLM(nn.Module):
    """Causal language model head over the trunk (flagship long-context
    model). setup() (not @nn.compact) so `hidden` can expose the trunk
    output without the head: at very long sequences the full [B, T, vocab]
    logits tensor is the HBM peak (seq 32k x vocab 32k in f32 = 4 GB), and
    the chunked loss (lm_loss_chunked) computes head+softmax per sequence
    chunk instead. Param paths ("trunk", "lm_head") are unchanged."""

    cfg: TransformerConfig
    attn_fn: AttnFn | None = None

    def setup(self):
        self.trunk = Transformer(self.cfg, self.attn_fn, name="trunk")
        self.lm_head = nn.Dense(
            self.cfg.vocab_size, dtype=self.cfg.dtype, param_dtype=jnp.float32,
            use_bias=False, name="lm_head",
        )

    def __call__(self, tokens, deterministic=True):
        h = self.trunk(tokens, deterministic)
        return self.lm_head(h).astype(jnp.float32)

    def hidden(self, tokens, deterministic=True):
        """Trunk output [B, T, H] (post final LayerNorm), no head."""
        return self.trunk(tokens, deterministic)


class TransformerClassifier(nn.Module):
    """Sequence classifier (BERT-style [CLS]-pooled) for the evaluator path."""

    cfg: TransformerConfig
    num_classes: int = 2
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, tokens, deterministic=True):
        h = Transformer(self.cfg, self.attn_fn, name="trunk")(tokens, deterministic)
        pooled = jnp.tanh(
            nn.Dense(self.cfg.hidden, dtype=self.cfg.dtype, param_dtype=jnp.float32,
                     name="pooler")(h[:, 0])
        )
        return nn.Dense(self.num_classes, dtype=self.cfg.dtype,
                        param_dtype=jnp.float32, name="cls")(pooled).astype(jnp.float32)


class BertMLM(nn.Module):
    """Masked-LM head over the trunk — the BERT-base pretraining objective
    (BASELINE.md workload 4's model)."""

    cfg: TransformerConfig
    attn_fn: AttnFn | None = None

    @nn.compact
    def __call__(self, tokens, deterministic=True):
        cfg = self.cfg
        h = Transformer(cfg, self.attn_fn, name="trunk")(tokens, deterministic)
        # BERT's MLM transform: dense + gelu + LN, then decode to vocab.
        h = nn.Dense(cfg.hidden, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlm_transform")(h)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="mlm_ln")(h)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                          param_dtype=jnp.float32, use_bias=False,
                          name="lm_head")(h)
        return logits.astype(jnp.float32)


def mlm_loss(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Cross entropy over masked positions only. mask: [B, T] 1.0 where the
    token was masked out (the 15% BERT selects)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / denom


def make_mlm_batch(
    rng: jax.Array, batch: int, seq: int, vocab_size: int,
    mask_rate: float = 0.15, mask_token: int = 103,  # BERT's [MASK]
) -> dict[str, jax.Array]:
    """Synthetic MLM batch: random tokens, `mask_rate` of them replaced by
    [MASK]; targets are the originals."""
    kt, km = jax.random.split(rng)
    targets = jax.random.randint(kt, (batch, seq), 0, vocab_size)
    mask = (jax.random.uniform(km, (batch, seq)) < mask_rate).astype(jnp.float32)
    tokens = jnp.where(mask.astype(bool), mask_token, targets)
    return {"tokens": tokens, "targets": targets, "mask": mask}


def lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy (shifted).

    Written as logsumexp(z) - z[target] rather than
    -log_softmax(z)[target]: identical math (same max-shift
    stabilization), but the [B, T, vocab] f32 log-probs tensor — the
    largest tensor of the whole step — is never materialized; the logits
    are read once for the reduction and the target logits come from a
    sparse gather. Measured ~3% of MoE/LM step time on-chip."""
    z = logits[:, :-1].astype(jnp.float32)
    tgt = tokens[:, 1:]
    lse = jax.nn.logsumexp(z, axis=-1)                      # [B, T-1]
    z_tgt = jnp.take_along_axis(z, tgt[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - z_tgt)


def lm_loss_chunked(
    h: jax.Array, head_kernel: jax.Array, tokens: jax.Array,
    chunk: int = 2048,
) -> jax.Array:
    """lm_loss without materializing the full [B, T, vocab] logits.

    Scans the sequence in chunks: each iteration projects one [B, chunk, H]
    slice of trunk output through the head and reduces its cross entropy,
    so peak logits memory is B*chunk*vocab instead of B*T*vocab — the
    difference between OOM and fitting at seq 32k on one v5e chip. AD
    transposes the scan, so the backward pass is chunked too (the head
    gradient accumulates across chunks). Numerics match lm_loss exactly:
    softmax is per-position, and the final mean is over the same T-1
    shifted targets.

    The per-chunk loss is jax.checkpoint'ed: without it, AD saves every
    iteration's logits as stacked scan residuals — a [T/chunk, B, chunk,
    vocab] f32 tensor, i.e. the FULL logits this function exists to avoid
    (measured: a 15.6 GB AllocateBuffer at seq 128k, round 5). With it
    the backward recomputes each chunk's head matmul from (h_c, kernel) —
    ~1.5% extra FLOPs — and peak logits memory is one chunk in both
    passes.
    """
    B, T, H = h.shape
    preds, tgt = h[:, :-1], tokens[:, 1:]  # predict token t+1 from h_t
    n = T - 1
    pad = (-n) % chunk
    if pad:
        preds = jnp.pad(preds, ((0, 0), (0, pad), (0, 0)))
        tgt = jnp.pad(tgt, ((0, 0), (0, pad)))
    mask = (jnp.arange(n + pad) < n).astype(jnp.float32)[None]  # [1, n+pad]
    k = (n + pad) // chunk
    # [k, B, chunk, ...] scan layout
    preds = preds.reshape(B, k, chunk, H).swapaxes(0, 1)
    tgt = tgt.reshape(B, k, chunk).swapaxes(0, 1)
    mask = jnp.broadcast_to(mask, (B, n + pad)).reshape(B, k, chunk).swapaxes(0, 1)

    kernel = head_kernel.astype(h.dtype)  # match the Dense's bf16 matmul

    # prevent_cse=False: the scan body already prevents CSE (JAX's own
    # guidance for remat under scan); the default would wrap each chunk's
    # recompute in optimization barriers that block fusion.
    @functools.partial(jax.checkpoint, prevent_cse=False)
    def chunk_nll(kern, h_c, t_c, m_c):
        # lse - z[target] == -log_softmax[target]; per-position, exact.
        logits = (h_c @ kern).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        z = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        return jnp.sum((lse - z) * m_c)

    def body(acc, xs):
        return acc + chunk_nll(kernel, *xs), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (preds, tgt, mask))
    return total / (B * n)
