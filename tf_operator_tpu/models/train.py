"""Generic trainer — the workload binary TrainJob pods run.

This is the data-plane entrypoint the operator's pods execute (the role
dist_mnist.py / keras_model_to_estimator.py played in the reference's
examples, SURVEY.md §3.4), TPU-native:

  python -m tf_operator_tpu.models.train --model resnet50 --steps 100

  1. jax.distributed from the operator-injected env (multi-process jobs)
  2. Mesh from TPUJOB_MESH (dp/fsdp/tp/sp axes)
  3. jitted SPMD train step (bf16 compute, donated state)
  4. synthetic data by default (bench determinism); progress as JSON lines
     on stdout and, when TPUJOB_METRICS_FILE is set, appended to that file
     (the hook bench.py uses to time startup->first-step and steps/sec).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Stdlib-only (tracer + phase accounting): safe before the jax import and
# cheap enough that the disabled path costs one attribute read per call.
from tf_operator_tpu import telemetry


def _emit(event: dict) -> None:
    line = json.dumps(event)
    print(line, flush=True)
    path = os.environ.get("TPUJOB_METRICS_FILE")
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")


def _start_profile(profile_dir: str) -> None:
    """Start an XProf device trace under profile_dir/<replica rank>.

    Replica type+index is unique per pod in every regime (chief-0 and
    worker-0 differ by type; non-distributed local pods have no distinct
    jax.process_index()). The reference delegated all profiling to
    cAdvisor/Prometheus node metrics (SURVEY.md §5); this is the TPU-native
    equivalent: per-op XProf timelines.
    """
    import jax

    rank = (f"{os.environ.get('TPUJOB_REPLICA_TYPE') or 'local'}-"
            f"{os.environ.get('TPUJOB_REPLICA_INDEX', '0')}")
    trace_dir = os.path.join(profile_dir, rank)
    jax.profiler.start_trace(trace_dir)
    _emit({"event": "profile_start", "dir": trace_dir})


def _trace_rank() -> str:
    """Replica identity for per-pod trace files — same naming as the
    jax.profiler dirs (_start_profile), so the two trace kinds pair up."""
    return (f"{os.environ.get('TPUJOB_REPLICA_TYPE') or 'local'}-"
            f"{os.environ.get('TPUJOB_REPLICA_INDEX', '0')}")


def _trace_window_check(args, steps_done: int) -> None:
    """Close the --trace-steps window: once N steps are recorded the
    tracer disables, so the rest of a long run costs nothing and the ring
    holds the WINDOW, not the last `capacity` events of the tail."""
    if args.trace and args.trace_steps and steps_done >= args.trace_steps:
        telemetry.get_tracer().enabled = False


def _maybe_export_trace(args) -> None:
    """Write the Chrome trace-event JSON (load it in Perfetto or
    chrome://tracing) and emit trace_done with its path."""
    if not getattr(args, "trace", False):
        return
    tracer = telemetry.get_tracer()
    tracer.enabled = False  # export is not part of the trace
    path = os.path.join(args.trace_dir or "traces",
                        f"{_trace_rank()}.trace.json")
    n = tracer.export(path)
    _emit({"event": "trace_done", "path": path, "events": n,
           "dropped_events": tracer.dropped_events})


def _is_checkpoint_writer() -> bool:
    """Chief (or worker-0 when no chief exists) writes checkpoints — the same
    role the reference gave worker-0/chief for summaries (SURVEY.md §3.4).
    A standalone run (no operator env) always writes."""
    rtype = os.environ.get("TPUJOB_REPLICA_TYPE", "").lower()
    if not rtype:
        return True
    if rtype in ("chief", "master"):
        return True
    if rtype != "worker" or os.environ.get("TPUJOB_REPLICA_INDEX", "0") != "0":
        return False
    # Worker-0 writes only when the job has no chief/master (one writer per
    # checkpoint dir); the injected ClusterSpec says whether one exists.
    try:
        cluster = json.loads(os.environ.get("TF_CONFIG", "{}")).get("cluster", {})
    except ValueError:
        cluster = {}
    return not ("chief" in cluster or "master" in cluster)


def _aux_tree(state) -> dict:
    """Resume payload beyond params (optimizer moments + f32 master copy,
    step counter, mutable model state). The optimizer state is stored as a
    flat leaf list — orbax does not round-trip namedtuple structure (tuples
    come back as lists) — and the resume side rebuilds it with the
    freshly-initialized state's treedef. Leaves keep their configured
    dtypes (bf16 moments save/restore as bf16; the f32 master as f32)."""
    import jax

    tree = {
        "step": state.step,
        "opt_leaves": list(jax.tree.leaves(state.opt_state)),
    }
    if state.model_state:
        tree["model_state"] = state.model_state
    return tree


# Trainer-side chaos directives (kill-at-step / hang-at-step /
# torn-checkpoint), set once per main() from TPUJOB_CHAOS / --chaos; None —
# the default — costs one `is None` check per boundary.
_chaos = None

# Progress heartbeat (TPUJOB_HEARTBEAT_FILE, runtime-injected): written at
# step boundaries so the operator's hang watchdog can tell a Running job
# from a wedged one. Module-global like _chaos (the two loops and the
# boundary helpers share it); None-path costs one `is None` check.
_heartbeat = None

# The live mesh, for the checkpoint sharding manifest (every save records
# the gang shape + per-leaf layout it was taken from, so a restore onto a
# DIFFERENT shape can reshard instead of guessing). Module-global like
# _chaos/_heartbeat: _save_checkpoint has ~6 call sites across both loops
# and the preemption path.
_mesh = None

# Whether saves also record the crc32 digest (the reshard bit-equality
# witness). Costs a full host-tree pass per save, so it is paid only when
# the job actually opted into reshaping (--allow-reshape /
# TPUJOB_ALLOW_RESHAPE — the operator injects the env on elastic jobs);
# the sharding manifest itself is cheap and always written.
_digest_saves = False


def _hb(step: int, force: bool = False) -> None:
    if _heartbeat is not None:
        _heartbeat.write(step, force=force)


def _boundary_chaos(done: int, start_step: int) -> None:
    """Step-boundary chaos hook shared by both loops: hang-at-step (stop
    making progress without exiting — the wedged-collective simulation the
    heartbeat watchdog exists for), then kill-at-step. Order matters: a
    directive pairing both at one step should go quiet BEFORE dying."""
    if _chaos is None:
        return
    d = _chaos.hang_at(done, start_step)
    if d is not None:
        from tf_operator_tpu import chaos as chaos_lib

        duration = d.params.get("duration")
        _emit({"event": "chaos_hang", "step": done, "duration": duration})
        chaos_lib.hang(duration)
    _chaos.maybe_kill(done, start_step)


def _save_checkpoint(ckpt_dir: str, step: int, state, final: bool = False,
                     keep: int = 0) -> float:
    """step_<N> holds params ONLY (the evaluator/external contract — cheap
    to restore, format-compatible with hand-written checkpoints);
    trainstate_<N> holds the resume payload. The aux dir is written first
    so any visible step_<N> has its trainstate beside it. Returns the
    save's wall-clock seconds — the preemption guard's estimate of what an
    emergency save will cost against the grace budget."""
    import jax

    from tf_operator_tpu.models import checkpoint as ckpt

    t0 = time.monotonic()
    aux = _aux_tree(state)
    host_aux = jax.device_get(aux)
    ckpt.save_named(ckpt_dir, f"trainstate_{step}", host_aux)
    host_params = jax.device_get(state.params)
    path = ckpt.save(ckpt_dir, step, host_params)
    # orbax coordinates the collective save, but mark_final/_emit/prune are
    # plain file IO: one writer only, or concurrent os.replace of the
    # shared .FINAL.tmp races (loser raises, failing a finished job).
    if jax.process_index() == 0:
        # Sharding manifest (topology-portable checkpoints): the gang
        # shape + per-leaf layout this save came from, and a crc32 of the
        # host bytes (the bit-equality witness the resumed event reports
        # back). Written after the orbax rename like the size census.
        from tf_operator_tpu.parallel import mesh as mesh_lib

        info = {
            "processCount": jax.process_count(),
            "deviceCount": jax.device_count(),
            "mesh": (mesh_lib.shape_dict(_mesh)
                     if _mesh is not None else {}),
            "leaves": ckpt.leaf_shardings(state.params),
            "auxLeaves": ckpt.leaf_shardings(aux),
        }
        if _digest_saves:
            info["digest"] = {"params": ckpt.tree_digest(host_params),
                              "trainstate": ckpt.tree_digest(host_aux)}
        ckpt.write_sharding_manifest(ckpt_dir, f"step_{step}", info)
        if final:
            ckpt.mark_final(ckpt_dir, step)
        _emit({"event": "checkpoint", "step": step, "path": path, "final": final})
        if keep:
            pruned = ckpt.prune_checkpoints(ckpt_dir, keep)
            if pruned:
                _emit({"event": "checkpoint_pruned", "steps": pruned,
                       "keep": keep})
        if _chaos is not None:
            torn = _chaos.tear_for_step(step)
            if torn is not None:
                from tf_operator_tpu import chaos as chaos_lib

                _chaos.state.mark(torn)
                damaged = chaos_lib.tear_checkpoint(
                    ckpt_dir, step, torn.params.get("mode", "truncate")
                )
                _emit({"event": "chaos_torn_checkpoint", "step": step,
                       "path": damaged})
    # A finished save is DURABLE progress: force the heartbeat past the
    # 2 Hz throttle so the operator (hang watchdog, chaos at_step
    # directives keyed on the heartbeat) sees the checkpointed step
    # promptly even when steps complete faster than the throttle window.
    _hb(step, force=True)
    return time.monotonic() - t0


def _try_resume(ckpt_dir: str | None, state, tx, mesh=None,
                allow_reshape: bool = False):
    """Restore the newest RESTORABLE checkpoint, if any. Returns
    (state, start_step).

    Topology portability: each checkpoint carries a sharding manifest
    (gang shape + per-leaf layout, written by _save_checkpoint). A
    candidate saved at a DIFFERENT shape (process count or mesh axis
    layout) is a FOREIGN-shape checkpoint: without `allow_reshape`
    (--allow-reshape / TPUJOB_ALLOW_RESHAPE) it degrades exactly like a
    corrupt one — skipped with a `resume_fallback` event, walk continues
    — never a crash. With the flag, restore RESHARDS: per-leaf global
    shapes are checked against the template first (a model-config change
    is a skip, not a guess), the host tree restores as usual, and the
    caller's shard_state lays every leaf out onto the CURRENT mesh by
    the sharding rules — params and optimizer state together. Leaves
    whose values depend on the gang size are re-derived, not restored:
    RNG streams key off the global step and the data loop's shard reader
    re-splits by the new process count. A checkpoint with NO sharding
    manifest (pre-manifest, hand-written) gets the census grace:
    restorable, but same-shape semantics only — with allow_reshape set,
    a resume_fallback event records that reshape verification was
    unavailable.
    The reference's contract was 'stable pod identity + restart semantics so
    TF can resume from its own checkpoints' (SURVEY.md §5); here the trainer
    itself resumes, so a pod restarted by the operator's restart policy
    continues the trajectory instead of starting over. A step_<N> without a
    trainstate_<N> (external/hand-written checkpoint) resumes params-only
    with a fresh optimizer.

    Torn-checkpoint hardening (the preemption scenario's second half): the
    walk goes BACKWARD through list_steps past steps whose manifest census
    fails (checkpoint.validate_step) or whose restore raises — each skip
    emits a `resume_fallback` event — so one corrupt latest checkpoint
    costs the steps since the previous valid one instead of turning a
    retryable failure into a permanent crash-loop. All-corrupt (and
    fresh-dir) degrade to a step-0 cold start with a warning.

    Mixed-precision state restores at each slab's CONFIGURED dtype (orbax
    casts to the restore template, so a legacy all-f32 trainstate also loads
    under a bf16-moment config). Params restore at the optimizer's master
    precision (f32 under master_weights — a legacy f32 step_<N> keeps its
    full precision, a new bf16 one upcasts exactly) and the bf16 compute
    copy is re-derived; on the params-only path under master_weights the
    optimizer re-inits from the RESTORED params so the f32 master matches
    the checkpoint, not the session's random init."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu import optim as optim_lib
    from tf_operator_tpu.models import checkpoint as ckpt
    from tf_operator_tpu.parallel.train_step import TrainState

    if not ckpt_dir:
        return state, 0
    all_steps = ckpt.list_steps(ckpt_dir)
    ordered = list(reversed(all_steps))  # newest first

    from tf_operator_tpu.parallel import mesh as mesh_lib

    cur_shape = {
        "processCount": jax.process_count(),
        "mesh": mesh_lib.shape_dict(mesh) if mesh is not None else {},
    }
    # Template SHAPES for the reshard global-shape check, read straight
    # off the live params (master_template changes only DTYPES, never
    # shapes — going through it, even under eval_shape, would execute
    # its concrete np.zeros and allocate a full f32 host tree just to
    # read shapes). Computed lazily the first time a foreign-shape
    # candidate is considered.
    tmpl_shapes_memo: list[dict] = []

    def template_shapes() -> dict:
        if not tmpl_shapes_memo:
            tmpl_shapes_memo.append({
                jax.tree_util.keystr(p): [int(d) for d in
                                          getattr(leaf, "shape", ())]
                for p, leaf in
                jax.tree_util.tree_flatten_with_path(state.params)[0]
            })
        return tmpl_shapes_memo[0]

    def candidate_gate(s: int) -> tuple[bool, bool, dict | None]:
        """(restorable, reshaped, sharding manifest) for step s — the
        census validity plus the topology gate. Deterministic from the
        shared volume + flags, so every replica reaches the same verdict
        (the broadcast agreement below then only guards VISIBILITY)."""
        if not ckpt.validate_step(ckpt_dir, s):
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": "invalid_checkpoint"})
            return False, False, None
        sm = ckpt.read_sharding_manifest(ckpt_dir, f"step_{s}")
        if sm is None:
            # Pre-manifest / hand-written checkpoint: unverifiable, not
            # invalid — restorable under same-shape semantics only.
            if allow_reshape:
                _emit({"event": "resume_fallback", "step": s,
                       "reason": "missing_sharding_manifest: shape "
                                 "unverifiable, same-shape restore only"})
            return True, False, None
        saved = {
            "processCount": int(sm.get("processCount") or 0),
            "mesh": {k: int(v)
                     for k, v in (sm.get("mesh") or {}).items()},
        }
        if saved == cur_shape:
            return True, False, sm
        if not allow_reshape:
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": (
                       f"foreign_shape: saved on "
                       f"{saved['processCount']} process(es), mesh "
                       f"{saved['mesh']} (running "
                       f"{cur_shape['processCount']}, "
                       f"{cur_shape['mesh']}); pass --allow-reshape to "
                       f"reshard")})
            return False, False, sm
        # Reshard path: the GLOBAL shapes must match the template leaf
        # for leaf — a mismatch is a model-config change, and walking
        # past it beats restoring garbage.
        saved_shapes = {k: v.get("shape")
                        for k, v in (sm.get("leaves") or {}).items()}
        if saved_shapes != template_shapes():
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": "reshard_shape_mismatch: per-leaf global "
                             "shapes differ from this model config"})
            return False, False, sm
        return True, True, sm

    def next_restorable(start_idx: int) -> tuple[int, int | None, bool,
                                                 dict | None]:
        """(index, step, reshaped, sharding manifest) of the first
        restorable candidate at/after start_idx. Lazy on purpose: only
        checkpoints actually walked PAST are validated (and get a
        resume_fallback event) — a stale torn step older than the chosen
        candidate costs nothing and emits nothing, and a long-retention
        dir is never fully os.walk'd inside the restart path."""
        i = start_idx
        while i < len(ordered):
            ok, reshaped, sm = candidate_gate(ordered[i])
            if ok:
                return i, ordered[i], reshaped, sm
            i += 1
        return len(ordered), None, False, None

    idx, last, reshaped, sharding_m = next_restorable(0)
    if jax.process_count() > 1:
        # Every replica independently reads the checkpoint dir; if visibility
        # differs (non-shared volume, storage lag) the replicas would resume
        # divergent states AND compile different scan unrolls — mismatched
        # collectives hang the job. The agreement collective must run on
        # EVERY process (sentinel -1 = sees nothing) BEFORE any early
        # return, else the check itself deadlocks. (Validation is a
        # deterministic read of the shared volume, so agreeing on the
        # chosen candidate subsumes agreeing on latest_step.)
        from jax.experimental import multihost_utils
        import numpy as np

        observed = -1 if last is None else last
        agreed = int(multihost_utils.broadcast_one_to_all(np.int32(observed)))
        if agreed != observed:
            raise RuntimeError(
                f"checkpoint visibility differs across replicas (this process "
                f"sees step {observed}, process 0 sees {agreed}) — mount a "
                f"shared --checkpoint-dir volume"
            )
    if last is None:  # step_0 is a valid (externally seeded) checkpoint
        if all_steps:
            print(
                f"warning: no restorable checkpoint under {ckpt_dir} "
                f"(all {len(all_steps)} step dirs failed validation) — "
                f"cold-starting from step 0",
                file=sys.stderr,
            )
            _emit({"event": "resume_fallback", "to_step": 0,
                   "reason": "no_valid_checkpoint",
                   "steps_seen": len(all_steps)})
        return state, 0
    p_template = jax.device_get(
        optim_lib.master_template(tx, jax.device_get(state.params))
    )
    params = None
    while last is not None:
        try:
            params = ckpt.restore(ckpt_dir, last, template=p_template)
            break
        except Exception as e:  # noqa: BLE001 — a torn tree raises anything
            if jax.process_count() > 1:
                # The replicas agreed on `last` only; silently walking
                # further here could diverge — fail loud, retry the pod.
                raise
            _emit({"event": "resume_fallback", "skipped_step": last,
                   "reason": f"restore_error: {type(e).__name__}: {e}"})
            idx, last, reshaped, sharding_m = next_restorable(idx + 1)
    if params is None:
        print(
            f"warning: every checkpoint under {ckpt_dir} failed to "
            f"restore — cold-starting from step 0",
            file=sys.stderr,
        )
        _emit({"event": "resume_fallback", "to_step": 0,
               "reason": "no_valid_checkpoint", "steps_seen": len(all_steps)})
        return state, 0
    step_arr = jnp.asarray(last, jnp.int32)
    opt_state, model_state, partial = state.opt_state, state.model_state, True
    try:
        if not ckpt.validate_named(ckpt_dir, f"trainstate_{last}"):
            # Torn aux payload with an intact params dir: params-only
            # resume (fresh optimizer) beats walking further back.
            _emit({"event": "resume_fallback", "skipped_step": last,
                   "reason": "invalid_trainstate", "params_only": True})
            raise FileNotFoundError(f"trainstate_{last}")
        aux = ckpt.restore_named(
            ckpt_dir, f"trainstate_{last}", template=jax.device_get(_aux_tree(state))
        )
    except Exception:  # noqa: BLE001 — any unreadable aux degrades, below
        # params-only checkpoint (or a trainstate written under a different
        # optimizer layout — orbax raises ValueError on the leaf-list arity
        # mismatch — or torn past its manifest): fresh optimizer, step from
        # the dir name. Under master_weights the fresh f32 master must
        # mirror the restored params, not the session's random init.
        if isinstance(tx, optim_lib.MixedPrecisionTransformation) \
                and tx.config.master_weights:
            opt_state = tx.init(params)
    else:
        step_arr = jnp.asarray(aux["step"], jnp.int32)
        opt_state = jax.tree.unflatten(
            jax.tree.structure(state.opt_state), aux["opt_leaves"]
        )
        model_state = aux.get("model_state", state.model_state)
        partial = False
    if jax.process_count() > 1:
        # The replicas already agreed on the STEP; they must also agree on
        # full-vs-params-only, or one replica trains with restored Adam
        # moments while another re-initialized them — shapes match, the
        # collectives run, and the model silently diverges. Runs on every
        # process (same rule as the step agreement above).
        from jax.experimental import multihost_utils
        import numpy as np

        mine = 1 if partial else 0
        agreed_partial = int(
            multihost_utils.broadcast_one_to_all(np.int32(mine))
        )
        if agreed_partial != mine:
            raise RuntimeError(
                f"trainstate_{last} visibility differs across replicas "
                f"(this process resumes {'params-only' if mine else 'full'}"
                f", process 0 {'params-only' if agreed_partial else 'full'})"
                f" — shared --checkpoint-dir volume lagging; retrying"
            )
    state = TrainState(
        step=step_arr, params=optim_lib.compute_params(tx, params),
        opt_state=opt_state, model_state=model_state,
    )
    start = int(step_arr)
    def _dtypes_match(saved_leaves, tree) -> bool:
        """crc32 bytes are only comparable when every leaf restored at
        its SAVED dtype — a master-weights f32 upcast of a bf16 compute
        checkpoint is a correct restore whose bytes legitimately differ,
        and reporting that as a digest mismatch would read as
        corruption."""
        got = {jax.tree_util.keystr(p): str(getattr(leaf, "dtype", ""))
               for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}
        want = {k: v.get("dtype") for k, v in (saved_leaves or {}).items()}
        return want == got

    event = {"event": "resumed", "from_step": start, "params_only": partial}
    saved_digest = (sharding_m.get("digest") or {}) if sharding_m else {}
    if saved_digest:
        # Bit-equality witness: crc32 of the restored host bytes vs what
        # the save recorded (only written when the job opted into
        # reshaping). Equal digests PROVE the round trip (incl. a
        # resharded one) returned exactly the saved state; trees whose
        # dtypes changed across the round trip are skipped, not reported
        # as mismatches.
        digest = {}
        if ("params" in saved_digest
                and _dtypes_match(sharding_m.get("leaves"), params)):
            digest["params"] = ckpt.tree_digest(params)
        if (not partial and "trainstate" in saved_digest
                and _dtypes_match(sharding_m.get("auxLeaves"), aux)):
            digest["trainstate"] = ckpt.tree_digest(aux)
        if digest:
            event["digest"] = digest
            event["saved_digest"] = {k: saved_digest[k] for k in digest}
    if reshaped:
        event["reshaped"] = {
            "from_processes": int(sharding_m.get("processCount") or 0),
            "from_mesh": sharding_m.get("mesh") or {},
            "to_processes": jax.process_count(),
            "to_mesh": cur_shape["mesh"],
        }
    _emit(event)
    return state, start


def _preempt_exit(args, guard, state, done, saver, last_save_s,
                  last_ckpt_step, st=None) -> int:
    """Graceful-preemption teardown at a step boundary: write an emergency
    checkpoint when the grace budget still covers the estimated save cost
    (skip it when the boundary already has a periodic save), emit the
    `preempted` event, export any trace, and hand back 128+signum for the
    operator's EXIT_CODE policy to classify as retryable."""
    saved = False
    skipped = None
    if saver and args.checkpoint_dir:
        if done == last_ckpt_step:
            saved = True  # this boundary's periodic save already landed
        elif guard.within_grace(last_save_s, args.preempt_grace):
            if st is not None:
                with st.phase("checkpoint"):
                    _save_checkpoint(args.checkpoint_dir, done, state,
                                     keep=args.keep_checkpoints)
            else:
                _save_checkpoint(args.checkpoint_dir, done, state,
                                 keep=args.keep_checkpoints)
            saved = True
        else:
            skipped = "grace_budget"
    event = {
        "event": "preempted",
        "step": done,
        "signal": guard.signal_name,
        "exit_code": guard.exit_code,
        "emergency_checkpoint": saved,
        "grace_s": args.preempt_grace,
        "elapsed_s": round(guard.elapsed(), 3),
    }
    if skipped:
        event["save_skipped"] = skipped
    _emit(event)
    _maybe_export_trace(args)
    # No distributed_goodbye: in a real eviction every replica got the
    # signal; synchronizing a teardown barrier against dying peers would
    # burn the grace window.
    return guard.exit_code


def _run_evaluator(args, model, params_template, make_batch, loss_fn,
                   guard) -> int:
    """Evaluator replica: follow the checkpoint stream until FINAL
    (the reference's Evaluator role, excluded from the ClusterSpec)."""
    import jax

    from tf_operator_tpu.models import checkpoint as ckpt

    if not args.checkpoint_dir:
        print("--eval requires --checkpoint-dir", file=sys.stderr)
        return 2

    @jax.jit
    def eval_loss(params, batch):
        loss, _ = loss_fn(params, {}, batch, jax.random.key(0))
        return loss

    seen: set[int] = set()
    evaluated = 0
    while True:
        step = ckpt.wait_for_new_step(
            args.checkpoint_dir, seen, timeout=args.eval_timeout,
            # The guard only LATCHES signals now, so without this check an
            # evaluator would sit out the whole eval timeout under SIGTERM
            # and die by the kubelet's SIGKILL instead of exiting cleanly.
            should_stop=lambda: guard.triggered,
        )
        if guard.triggered:
            _emit({"event": "preempted", "role": "evaluator",
                   "signal": guard.signal_name, "exit_code": guard.exit_code,
                   "checkpoints_evaluated": evaluated})
            return guard.exit_code
        if step is None:
            final = ckpt.final_step(args.checkpoint_dir)
            if final is not None and final in seen:
                break  # stream complete
            print(f"evaluator: no new checkpoint in {args.eval_timeout}s",
                  file=sys.stderr)
            # No distributed teardown: the evaluator is excluded from
            # the SPMD process world (cluster_spec only enrolls
            # chief/master/worker), so it is always single-process.
            return 1 if evaluated == 0 else 0
        seen.add(step)
        params = ckpt.restore(args.checkpoint_dir, step, template=params_template)
        # Fixed keys -> the same eval batches every round, generated lazily
        # (materializing all of them up front would hold steps×batch arrays).
        with telemetry.span("eval", checkpoint_step=step, n_batches=args.steps):
            losses = [
                float(eval_loss(params, make_batch(jax.random.key(10_000 + i))))
                for i in range(args.steps)
            ]
        evaluated += 1
        _emit({
            "event": "eval",
            "checkpoint_step": step,
            "eval_loss": round(sum(losses) / len(losses), 6),
            "n_batches": args.steps,
        })
    _emit({"event": "eval_done", "checkpoints_evaluated": evaluated})
    return 0


def _train_on_dataset(args, state, start_step, loss_fn, tx, mesh, rules,
                      saver, t_start, guard, xla_options=None) -> int:
    """Real-data loop: host batches from the sharded dataset, staged onto
    the device so the transfer of batch i+K rides under the compute of
    batch i. Each process reads its own shards (shard_from_env) and feeds
    its slice of the GLOBAL batch.

    Two ingest modes (--input-staging): "prefetch" is the PR-1 double-
    buffered device_put thread (kept as the continuity baseline the bench's
    unstaged point tracks); "staged" is the round-7 staging ring
    (data/staging.py) — wire-dtype control, chunked puts, and first-class
    transfer/overlap accounting. Both route through the same on-device
    preprocess hook, so the uint8 wire normalizes inside the jitted step."""
    import jax

    from tf_operator_tpu.data import (
        ShardedDataset,
        prefetch_to_device,
        shard_from_env,
        stage_to_device,
    )
    from tf_operator_tpu.data import staging as staging_lib
    from tf_operator_tpu.parallel import mesh as mesh_lib
    from tf_operator_tpu.parallel.train_step import make_train_step

    nprocs = jax.process_count()
    if args.batch % nprocs:
        raise SystemExit(f"--batch {args.batch} not divisible by {nprocs} processes")
    reader, readers = shard_from_env()
    ds = ShardedDataset(args.data_dir, reader, readers)
    # start_batch keeps a resumed run on the uninterrupted batch sequence
    # (one local batch per global step). The stats dicts measure how much
    # of the input path (host batch production + host->device transfer)
    # actually hides under compute — reported in the done event so the
    # bench can quantify the overlap instead of asserting it.
    host_it = ds.batches(args.batch // nprocs, seed=0, start_batch=start_step)
    batch_sh = mesh_lib.batch_sharding(mesh)
    prefetch_stats: dict = {}
    staging_stats: dict = {}
    staging_tune = None
    if args.input_staging == "staged":
        lanes, chunks = args.staging_lanes, args.staging_chunks
        if args.staging_tune:
            # Peek ONE host batch, probe {lanes x chunks} against the live
            # link with copies of it, then chain it back in front — the
            # training trajectory is byte-identical to an untuned run
            # (pinned by test), only the engine geometry changes.
            import itertools

            first = next(host_it)
            # depth = the run's real ring depth, so every probe runs the
            # geometry the job will (the ring caps lanes at depth — a
            # winner probed at a deeper ring would lock an unprobed
            # configuration)
            staging_tune = staging_lib.autotune_staging(
                first, sharding=batch_sh, wire_dtype=args.wire_dtype,
                codec=args.wire_codec, depth=args.staging_depth,
            )
            lanes, chunks = staging_tune["lanes"], staging_tune["chunks"]
            host_it = itertools.chain([first], host_it)
            _emit({"event": "staging_tuned", "lanes": lanes,
                   "chunks": chunks,
                   "mb_per_s": staging_tune["mb_per_s"],
                   "probe_s": staging_tune["probe_s"]})
        it = stage_to_device(
            host_it,
            depth=args.staging_depth,
            sharding=batch_sh,
            chunks=chunks,
            wire_dtype=args.wire_dtype,
            stats=staging_stats,
            lanes=lanes,
            codec=args.wire_codec,
        )
    else:
        it = prefetch_to_device(
            (staging_lib.to_wire(b, args.wire_dtype) for b in host_it),
            depth=2,
            sharding=batch_sh,
            stats=prefetch_stats,
        )
    _, compile_step = make_train_step(
        loss_fn, tx, mesh, rules=rules, remat=args.remat,
        # uint8 wire batches normalize on device, inside the step (batch
        # args are not donated — see make_train_step's donation note).
        preprocess_fn=staging_lib.make_preprocess_fn(),
    )

    batch = next(it)
    step = compile_step(state, batch, compiler_options=xla_options)
    state, metrics = step(state, batch, jax.random.key(start_step))
    # Host transfer (block_until_ready is a no-op through the axon tunnel):
    # startup_s must include the first step's device execution.
    first_loss = float(metrics["loss"])
    t_first = time.time()
    done = start_step + 1
    _emit(
        {
            "event": "first_step",
            "t": t_first,
            "startup_s": round(t_first - t_start, 3),
            "steps_in_first_call": 1,
            "loss": first_loss,
            "mesh": dict(mesh.shape),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": len(jax.devices()),
            "data_dir": args.data_dir,
            "local_samples": ds.num_samples,
        }
    )
    _hb(done, force=True)  # first optimizer step landed: liveness + step
    profiling = bool(args.profile_dir) and done < args.steps
    if profiling:
        _start_profile(args.profile_dir)
    # Same latency-hiding as the scanned loop: fetch step i's loss after
    # dispatching step i+1 so the transfer rides under compute (the
    # immediate fetch otherwise idles the chip one full tunnel round trip
    # per emit). Only the window-closing fetch blocks.
    # Phase accounting (telemetry/phases.py): every steady step decomposes
    # into data_wait / dispatch / device_blocked / checkpoint (+ "other"
    # residual) telescoping exactly to the step's wall-clock; the done
    # event carries the per-step distribution, not just the mean.
    t0 = time.time()
    pending = None
    last_save_s, last_ckpt_step = 0.0, -1
    acct = telemetry.make_step_accounting()
    while done < args.steps:
        _trace_window_check(args, done - start_step - 1)
        with acct.step(done + 1) as st:
            with st.phase("data_wait"):
                batch = next(it)
            with st.phase("dispatch"):
                state, metrics = step(state, batch, jax.random.key(done))
            done += 1
            if pending is not None:
                pstep, pmetrics = pending
                if pstep % args.log_every == 0:
                    with st.phase("device_blocked"):
                        ploss = float(pmetrics["loss"])
                    _emit({"event": "progress", "step": pstep,
                           "loss": ploss})
            pending = (done, metrics)
            if (saver and args.checkpoint_every and done < args.steps
                    and done % args.checkpoint_every == 0):
                with st.phase("checkpoint"):
                    last_save_s = _save_checkpoint(
                        args.checkpoint_dir, done, state,
                        keep=args.keep_checkpoints)
                    last_ckpt_step = done
            # Step boundary: the progress heartbeat records the completed
            # step, chaos hang/kill-at-step fire here, and a latched
            # preemption signal (SIGTERM/SIGINT/SIGUSR1 — real or chaos-
            # injected) turns into emergency-checkpoint + exit 128+signum.
            _hb(done)
            _boundary_chaos(done, start_step)
            if guard.triggered:
                return _preempt_exit(args, guard, state, done, saver,
                                     last_save_s, last_ckpt_step, st)
    if pending is not None:
        # Real window closure: a host transfer (block_until_ready is a
        # no-op through the axon tunnel).
        pstep, pmetrics = pending
        closing_loss = float(pmetrics["loss"])
    dt = time.time() - t0
    if pending is not None:
        # The loop exits only at done == args.steps, so the final progress
        # event (pstep == args.steps) always emits.
        _emit({"event": "progress", "step": pstep, "loss": closing_loss})
    if profiling:
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": args.steps - start_step - 1})
    if saver:
        _save_checkpoint(args.checkpoint_dir, args.steps, state, final=True,
                         keep=args.keep_checkpoints)
    # The final step must land in the heartbeat whatever the throttle did
    # at intermediate boundaries (the watchdog/collector read it back).
    _hb(args.steps, force=True)
    steady = args.steps - start_step - 1
    sps = round(steady / dt, 4) if steady > 0 else None
    from tf_operator_tpu.data.prefetch import overlap_efficiency

    telem = acct.summary()
    done_event = {
        "event": "done",
        "t": time.time(),
        "steps": args.steps,
        "steady_steps_per_sec": sps,
        "examples_per_sec": round(steady * args.batch / dt, 4) if steady > 0 else None,  # 4 dp: 2-dp quantized batch-1 long-context rows by +-2.6%
        "final_loss": float(metrics["loss"]),
        "total_s": round(time.time() - t_start, 3),
        # Per-step wall-clock distribution + telescoping phase breakdown
        # (telemetry/phases.py): p99 stalls are invisible in the mean.
        "step_time_s": telem["step_time_s"] if telem else None,
        "phase_breakdown": telem["phase_breakdown"] if telem else None,
    }
    if args.input_staging == "staged":
        # First-class transfer + overlap accounting from the staging ring's
        # own timers (data/staging.py): the bench's staged point reads these
        # as transfer_mb_per_s / input_overlap_fraction.
        rate = staging_lib.transfer_mb_per_s(staging_stats)
        overlap = staging_lib.input_overlap_fraction(staging_stats)
        done_event["staging"] = {
            "depth": args.staging_depth,
            # chunks/lanes that RAN (the tuner may have overridden the
            # flags; chunks_effective/lanes_effective say what the engine
            # then degraded them to per-array / per-path)
            "chunks": chunks,
            # what the knob actually did: degraded per-array (size/shard
            # divisibility) and inactive on multi-process jobs — a tuned
            # --staging-chunks that reads back 1 here did nothing
            "chunks_effective": staging_stats.get("chunks_effective"),
            "lanes": lanes,
            "lanes_effective": staging_stats.get("lanes_effective"),
            "wire_dtype": args.wire_dtype,
            "codec": args.wire_codec,
            "batches": staging_stats.get("batches_consumed"),
            # staged >= consumed: the ring reads ahead up to `depth`
            # batches the step loop never drained (bytes_staged covers
            # staged, so the two are reported together)
            "batches_staged": staging_stats.get("batches_staged"),
            "bytes_staged_mb": round(
                staging_stats.get("bytes_staged", 0) / 1e6, 3),
            "transfer_s": round(staging_stats.get("transfer_s", 0.0), 3),
            # union wall-clock with >= 1 lane on the wire — the clock
            # behind transfer_mb_per_s (== transfer_s when single-lane)
            "transfer_busy_s": round(
                staging_stats.get("transfer_busy_s", 0.0), 3),
            "transfer_mb_per_s": round(rate, 2) if rate is not None else None,
            "input_overlap_fraction": (
                round(overlap, 4) if overlap is not None else None),
            # consumer wall-clock decomposition; wait + busy == wall by
            # construction (tests pin it), so nothing is unaccounted.
            "wall_s": round(staging_stats.get("wall_s", 0.0), 3),
            "consumer_wait_s": round(
                staging_stats.get("consumer_wait_s", 0.0), 3),
            "consumer_busy_s": round(
                staging_stats.get("consumer_busy_s", 0.0), 3),
        }
        if args.wire_codec != "none":
            # Codec cost/benefit ledger: what a compressed remote wire
            # would carry vs what the codec burned in lane CPU — the
            # decision input for a compressed tunnel protocol.
            enc = staging_stats.get("bytes_encoded", 0)
            raw = staging_stats.get("bytes_staged", 0)
            done_event["staging"].update({
                "bytes_encoded_mb": round(enc / 1e6, 3),
                "codec_ratio": round(raw / enc, 3) if enc else None,
                "encode_s": round(staging_stats.get("encode_s", 0.0), 3),
                "decode_s": round(staging_stats.get("decode_s", 0.0), 3),
            })
        if staging_tune is not None:
            # The startup probe table (autotune_staging): why the tuner
            # locked this {lanes x chunks} — audit trail for the bench.
            done_event["staging"]["tune"] = staging_tune
    else:
        # Measured input-path overlap (VERDICT r5 weak-#4): what share
        # of host production + host->device transfer rode under
        # compute, from the prefetcher's own timers.
        overlap = overlap_efficiency(prefetch_stats)
        done_event["prefetch"] = {
            "batches": prefetch_stats.get("batches_consumed"),
            "input_s": round(prefetch_stats.get("input_s", 0.0), 3),
            "consumer_wait_s": round(
                prefetch_stats.get("consumer_wait_s", 0.0), 3),
            "overlap_efficiency": (
                round(overlap, 4) if overlap is not None else None),
        }
    _emit(done_event)
    _maybe_export_trace(args)
    # Synchronized multi-process exit (no-op single-process): see
    # parallel.distributed.distributed_goodbye.
    from tf_operator_tpu.parallel.distributed import distributed_goodbye

    distributed_goodbye()
    return 0


def _logits_bytes(args, mesh, vocab_size: int) -> float:
    """Per-device f32 logits bytes for the chunked-CE cutover.

    Divides the global [B, T, V] tensor by dp x fsdp only (batch dim,
    sharded by construction: the trainer puts the batch dim of every input
    on dp/fsdp). tp AND sp are deliberately EXCLUDED. tp shards the vocab
    dim, and the loss then gathers along that sharded dim
    (take_along_axis), which GSPMD may resolve by all-gathering the
    full-vocab logits per device. sp's seq sharding of T reaches the
    logits only if GSPMD propagates the attention shard_map's seq
    sharding through the blocks and lm_head — the trainer never shards
    the batch's seq dim itself, so on a mesh where that propagation
    fails the per-device logits are 1/sp bigger than the estimate and
    the one-shot head OOMs (round-4 advice). Conservative over-estimate
    -> worst case is the slightly slower chunked head."""
    from tf_operator_tpu.parallel import mesh as mesh_lib

    shards = max(1, mesh_lib.axis_size(mesh, "dp")
                 * mesh_lib.axis_size(mesh, "fsdp"))
    return 4.0 * args.batch * args.seq * vocab_size / shards


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--model",
        default="mnist-mlp",
        choices=["mnist-mlp", "mnist-conv", "resnet18", "resnet50",
                 "transformer-lm", "bert-base", "bert-tiny", "moe-lm"],
    )
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4,
                    help="transformer-lm/moe-lm depth")
    ap.add_argument("--hidden", type=int, default=512,
                    help="transformer-lm/moe-lm width")
    ap.add_argument("--heads", type=int, default=8,
                    help="transformer-lm/moe-lm attention heads")
    ap.add_argument("--moe-dispatch", default="dense",
                    choices=["dense", "sparse"],
                    help="moe-lm token dispatch: dense = GShard capacity "
                         "einsums (ep-shardable); sparse = dropless sorted "
                         "ragged matmul (ep=1 perf path)")
    ap.add_argument("--remat-save-flash", action="store_true",
                    help="with --remat (transformer-lm): save the flash "
                         "kernel's (o, lse) residuals so the backward "
                         "replays only linear ops, never the O(T^2) "
                         "kernel. Costs ~[B,T,H] bf16 per layer of HBM. "
                         "Fits (and is the bench config) at single-chip "
                         "64k since the round-5 chunked-CE fix; at 128k "
                         "use --remat-save-flash-layers instead")
    ap.add_argument("--remat-save-flash-layers", type=int, default=0,
                    help="with --remat (transformer-lm): save the flash "
                         "residuals for the FIRST K layers only (memory->"
                         "speed dial where saving all layers OOMs)")
    ap.add_argument("--remat", action="store_true",
                    help="activation checkpointing: rematerialize the loss, "
                         "and (transformer-lm) each block — saves only "
                         "block inputs for the backward at ~33%% extra "
                         "backward FLOPs; required for seq >= 64k on one "
                         "v5e chip")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--optimizer", default="adamw", choices=["adam", "adamw"])
    ap.add_argument("--moment-dtype", default="f32", choices=["f32", "bf16"],
                    help="Adam moment (mu/nu) STORAGE dtype; update math is "
                         "always f32. bf16 halves the optimizer-moment HBM "
                         "slab and its per-step read+write traffic "
                         "(docs/perf.md round-6 section)")
    ap.add_argument("--master-weights", action="store_true",
                    help="keep the authoritative f32 param copy in the "
                         "optimizer state and train on bf16 compute params "
                         "re-derived from it each step: fwd/bwd read 2-byte "
                         "weights while updates accumulate in f32. "
                         "Checkpoints round-trip both copies; legacy f32 "
                         "checkpoints still load (params-only, master "
                         "rebuilt from them)")
    ap.add_argument("--log-every", type=int, default=20)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="chief/worker-0 writes orbax checkpoints here; the "
                         "Evaluator replica follows them (--eval)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="save every N steps (default: once at the end)")
    ap.add_argument("--allow-reshape", action="store_true",
                    help="accept a checkpoint saved at a DIFFERENT gang "
                         "shape (process count / mesh): restore reshards "
                         "every leaf (params + optimizer state) onto the "
                         "current mesh via the checkpoint's sharding "
                         "manifest. Without this flag a foreign-shape "
                         "checkpoint is skipped by the resume walk like a "
                         "corrupt one. The operator sets "
                         "TPUJOB_ALLOW_RESHAPE=1 on pods of jobs with "
                         "recovery.elastic.reshapeOnRecovery")
    ap.add_argument("--keep-checkpoints", type=int, default=0,
                    help="retention: after each save keep only the newest K "
                         "step checkpoints (params + trainstate + manifests) "
                         "and prune the rest; 0 (default) keeps everything. "
                         "Orphaned orbax tmp dirs are swept at startup "
                         "either way")
    ap.add_argument("--preempt-grace", type=float, default=30.0,
                    help="graceful-preemption budget in seconds, measured "
                         "from SIGTERM/SIGINT/SIGUSR1 receipt (the window "
                         "before the kubelet's SIGKILL): the trainer "
                         "finishes the in-flight step and writes an "
                         "emergency checkpoint only when the estimated "
                         "save still fits the budget; 0 never attempts "
                         "the emergency save. Exit is 128+signum either "
                         "way (143/130/138 — retryable under EXIT_CODE)")
    ap.add_argument("--chaos", default=None, metavar="SPEC",
                    help="fault-injection spec (same grammar as "
                         "TPUJOB_CHAOS, which it overrides): e.g. "
                         "'kill:step=12,signal=TERM' or "
                         "'torn:step=8;stall:every=3,delay=0.2' — see "
                         "docs/robustness.md")
    ap.add_argument("--eval", action="store_true",
                    help="evaluator mode: poll --checkpoint-dir, restore and "
                         "evaluate each new checkpoint until FINAL")
    ap.add_argument("--eval-timeout", type=float, default=600.0)
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler (XProf/TensorBoard) trace of "
                         "the steady-state window to this directory")
    ap.add_argument("--trace", action="store_true",
                    help="record host-side spans (step phases, input "
                         "staging, checkpoint IO) in the in-process tracer "
                         "and export Chrome trace-event JSON at exit "
                         "(Perfetto / chrome://tracing). Composes with "
                         "--profile-dir: this is the host timeline, XProf "
                         "is the device one")
    ap.add_argument("--trace-dir", default=None,
                    help="directory for the trace file "
                         "(<replica rank>.trace.json; default ./traces)")
    ap.add_argument("--trace-steps", type=int, default=0,
                    help="stop recording after this many steady steps "
                         "(0 = the whole run, bounded by the tracer's "
                         "ring buffer)")
    ap.add_argument("--xla-option", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="per-executable XLA compiler option (repeatable), "
                         "forwarded via jit(...).lower().compile(). sparse "
                         "moe-lm on TPU defaults to "
                         "xla_tpu_scoped_vmem_limit_kib=49152: ragged_dot's "
                         "mosaic kernel at bench shapes needs ~22M (fwd) / "
                         "~34M (bwd) scoped VMEM vs the 16M default")
    ap.add_argument("--data-dir", default=None,
                    help="train on a sharded on-disk dataset (data/dataset.py "
                         "layout; keys must match the model's batch keys) "
                         "instead of synthetic data; --batch is the GLOBAL "
                         "batch, sharded across processes")
    ap.add_argument("--input-staging", default="prefetch",
                    choices=["prefetch", "staged"],
                    help="with --data-dir: host->device ingest mode. "
                         "'prefetch' = the double-buffered transfer thread "
                         "(continuity baseline); 'staged' = the staging "
                         "ring (data/staging.py): K device-batch slots, "
                         "optional chunked puts, and first-class "
                         "transfer-rate/overlap accounting in the done "
                         "event")
    ap.add_argument("--staging-depth", type=int, default=2,
                    help="staging ring size K: batches resident on device "
                         "ahead of the consumer (2 = double buffering)")
    ap.add_argument("--staging-chunks", type=int, default=1,
                    help="concurrent device_put transfers per staged array "
                         "(split along the batch dim, reassembled "
                         "on-device); >1 raises the effective rate on "
                         "links one serial put can't fill. Degrades "
                         "per-array to the largest feasible count (size "
                         "threshold, shard divisibility; inactive on "
                         "multi-process jobs) — the done event's "
                         "staging.chunks_effective records what ran")
    ap.add_argument("--staging-lanes", type=int, default=1,
                    help="transfer threads feeding the staging ring "
                         "CONCURRENTLY (each issues its own chunked "
                         "device_puts; ordered reassembly keeps exact "
                         "batch order). >1 raises the effective rate on "
                         "links where one put stream can't fill the pipe. "
                         "Capped at --staging-depth and inactive on "
                         "multi-process jobs — the done event's "
                         "staging.lanes_effective records what ran")
    ap.add_argument("--staging-tune", action="store_true",
                    help="micro-probe {lanes x chunks} combinations "
                         "against the live host->device link for a few "
                         "batches at startup and lock the best (overrides "
                         "--staging-lanes/--staging-chunks); the probe "
                         "table lands in the done event's staging.tune. "
                         "The probed batch is chained back into the "
                         "stream, so the training trajectory is identical "
                         "to an untuned run")
    ap.add_argument("--wire-codec", default="none",
                    choices=["none", "zlib"],
                    help="lossless wire compression for staged ingest: "
                         "encoded on the producer leg, decoded host-side "
                         "by the lane just before device_put (numerics "
                         "bit-identical). On a single-host runtime this "
                         "only MEASURES what a compressed remote wire "
                         "would save (staging.bytes_encoded_mb/"
                         "codec_ratio vs encode_s/decode_s)")
    ap.add_argument("--wire-dtype", default="auto",
                    choices=["auto", "uint8", "f32"],
                    help="with --data-dir: host->device wire format. auto = "
                         "ship arrays as stored (uint8 images stay uint8, "
                         "4x less wire than f32; normalization happens "
                         "on-device inside the step); uint8 = assert the "
                         "cheap wire (error if the dataset stores float "
                         "images); f32 = normalize on host and ship f32 "
                         "(the parity reference path)")
    args = ap.parse_args(argv)

    # Flag-only invariants fail HERE — before jax import, device dial, state
    # build, or checkpoint resume (minutes on a tunneled chip), and on every
    # path including --eval and resumed-complete early returns.
    if ((args.remat_save_flash or args.remat_save_flash_layers)
            and not args.remat):
        ap.error("--remat-save-flash[-layers] requires --remat (it selects "
                 "WHICH residuals per-layer remat keeps)")
    if args.remat_save_flash and args.remat_save_flash_layers:
        ap.error("--remat-save-flash (all layers) conflicts with "
                 "--remat-save-flash-layers K (a subset): pick one — the "
                 "all-layers flag would silently win and can OOM exactly "
                 "where the K dial was chosen to fit")
    if args.remat_save_flash_layers < 0:
        ap.error("--remat-save-flash-layers must be >= 0")
    for kv in args.xla_option:
        if "=" not in kv:
            ap.error(f"--xla-option must be KEY=VALUE, got {kv!r}")
    if args.staging_depth < 1:
        ap.error("--staging-depth must be >= 1")
    if args.staging_chunks < 1:
        ap.error("--staging-chunks must be >= 1")
    if args.staging_lanes < 1:
        ap.error("--staging-lanes must be >= 1")
    if not args.data_dir and (args.input_staging != "prefetch"
                              or args.wire_dtype != "auto"
                              or args.wire_codec != "none"
                              or args.staging_depth != 2
                              or args.staging_chunks != 1
                              or args.staging_lanes != 1
                              or args.staging_tune):
        ap.error("--input-staging/--wire-dtype/--wire-codec/"
                 "--staging-depth/--staging-chunks/--staging-lanes/"
                 "--staging-tune shape the --data-dir ingest path; "
                 "without --data-dir batches are synthesized on device "
                 "and there is no wire to shape")
    if (args.input_staging == "prefetch"
            and (args.staging_depth != 2 or args.staging_chunks != 1
                 or args.staging_lanes != 1 or args.staging_tune
                 or args.wire_codec != "none")):
        ap.error("--staging-depth/--staging-chunks/--staging-lanes/"
                 "--staging-tune/--wire-codec configure the staging "
                 "RING; with --input-staging prefetch they would be "
                 "silently ignored — pass --input-staging staged")
    if (args.trace_dir is not None or args.trace_steps) and not args.trace:
        ap.error("--trace-dir/--trace-steps shape the span trace; pass "
                 "--trace to enable it (they would otherwise be silently "
                 "ignored)")
    if args.trace_steps < 0:
        ap.error("--trace-steps must be >= 0")
    if args.preempt_grace < 0:
        ap.error("--preempt-grace must be >= 0")
    if args.keep_checkpoints < 0:
        ap.error("--keep-checkpoints must be >= 0")
    if args.keep_checkpoints and not args.checkpoint_dir:
        ap.error("--keep-checkpoints prunes --checkpoint-dir; without one "
                 "there is nothing to retain")
    if args.allow_reshape and not args.checkpoint_dir:
        ap.error("--allow-reshape shapes the --checkpoint-dir resume walk; "
                 "without one there is nothing to restore")
    from tf_operator_tpu import chaos as chaos_lib

    global _chaos
    chaos_env_prev = os.environ.get(chaos_lib.ENV_CHAOS)
    try:
        if args.chaos is not None:
            # Validate BEFORE mutating the env — a typo'd spec must fail
            # here without leaking into os.environ. The env write is the
            # one cross-layer channel (the staging ring and the fake
            # apiserver read it); main's finally restores it.
            chaos_lib.parse_chaos(args.chaos)
            os.environ[chaos_lib.ENV_CHAOS] = args.chaos
        _chaos = chaos_lib.TrainerChaos.from_env()
    except ValueError as e:
        ap.error(str(e))
    if args.trace:
        # Fresh window: clear() also restarts the ts epoch, so in-process
        # re-runs (tests, notebooks) don't leak a prior run's spans into
        # this run's export.
        telemetry.configure(enabled=True).clear()

    # Graceful preemption: handlers latch SIGTERM/SIGINT/SIGUSR1; the train
    # loops poll at step boundaries. Installed before the (slow) jax import
    # so a signal during startup is latched rather than fatal, and after
    # flag validation so ap.error paths never touch process-wide signal
    # disposition (in-process CLI tests included).
    from tf_operator_tpu.utils.preemption import HeartbeatWriter, PreemptionGuard

    guard = PreemptionGuard()
    guard.install()
    # Liveness from the very first moment: an immediate forced heartbeat
    # (before the slow jax import) tells the hang watchdog this generation
    # is alive even while startup/compile produces no step boundaries.
    global _heartbeat
    _heartbeat = HeartbeatWriter.from_env()
    _hb(0, force=True)

    try:
        return _run_trainer(args, guard)
    finally:
        # In-process-caller hygiene: hand back signal disposition and the
        # chaos env exactly as we found them, and drop the chaos state, so
        # a later chaos-free run in the same process stays chaos-free and
        # the host's Ctrl-C semantics survive this function.
        guard.uninstall()
        _chaos = None
        _heartbeat = None
        global _mesh, _digest_saves
        _mesh = None
        _digest_saves = False
        if args.chaos is not None:
            if chaos_env_prev is None:
                os.environ.pop(chaos_lib.ENV_CHAOS, None)
            else:
                os.environ[chaos_lib.ENV_CHAOS] = chaos_env_prev



def _run_trainer(args, guard) -> int:
    """Everything after flag validation and signal-guard install: device
    dial, model/optimizer build, resume, and the training loops. Split
    from main() so its MANY return paths share main's one finally (guard
    uninstall + chaos-env restore)."""

    t_start = time.time()
    _emit({"event": "start", "t": t_start, "model": args.model})

    from tf_operator_tpu.parallel.distributed import initialize_from_env

    initialize_from_env()
    # jax.distributed.initialize installs XLA's TSL PreemptionNotifier
    # SIGTERM handler over the guard's — without re-asserting, a
    # multi-process gang steps straight through a graceful eviction and
    # gets SIGKILLed checkpointless by the drain discipline.
    guard.reassert()

    import jax

    # Dial the accelerator while the rest of the stack imports: attaching a
    # (possibly tunneled) TPU backend is network-bound and independent of
    # the CPU-bound flax/optax import work, so the two overlap. The main
    # thread re-joins at mesh_from_env()'s jax.devices() call; an attach
    # error surfaces there, not in this daemon thread.
    import threading

    threading.Thread(
        target=lambda: jax.devices(), daemon=True, name="backend-dial"
    ).start()

    import jax.numpy as jnp

    from tf_operator_tpu.parallel import mesh as mesh_lib
    from tf_operator_tpu.parallel import sharding_rules
    from tf_operator_tpu.parallel.ring_attention import make_attention_fn
    from tf_operator_tpu.parallel.train_step import (
        create_train_state,
        make_scanned_train_step,
        shard_state,
        state_shardings,
    )
    from tf_operator_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    mesh = mesh_lib.mesh_from_env()
    global _mesh, _digest_saves
    _mesh = mesh  # checkpoint sharding manifests record the save-time mesh
    allow_reshape = (args.allow_reshape
                     or os.environ.get("TPUJOB_ALLOW_RESHAPE") == "1")
    _digest_saves = allow_reshape
    # Segment timestamps (bench.py turns these into the startup breakdown
    # the north-star latency metric is judged on).
    _emit({"event": "jax_ready", "t": time.time(),
           "backend": jax.default_backend()})
    _hb(0, force=True)  # startup liveness milestone (pre state-build)
    rules = None
    # Each branch defines init_params(rng) -> (params, model_state) as a
    # TRACEABLE closure: the whole setup (init + optimizer) compiles into
    # one program with sharded outputs (see build_state below), instead of
    # dispatching dozens of tiny init ops — each a round-trip on a
    # tunneled chip — before training starts.

    if args.model in ("mnist-mlp", "mnist-conv"):
        from tf_operator_tpu.models import mnist as M

        model = M.MLP() if args.model == "mnist-mlp" else M.ConvNet()

        def init_params(rng):
            x = jnp.zeros((1, 28, 28), jnp.float32)
            return model.init(rng, x)["params"], {}

        def make_batch(rng):
            kx, ky = jax.random.split(rng)
            return {
                "x": jax.random.normal(kx, (args.batch, 28, 28)),
                "y": jax.random.randint(ky, (args.batch,), 0, 10),
            }

        def loss_fn(params, model_state, batch, rng):
            logits = model.apply({"params": params}, batch["x"])
            return M.cross_entropy_loss(logits, batch["y"]), model_state

    elif args.model in ("resnet18", "resnet50"):
        from tf_operator_tpu.models import mnist as M  # loss helpers
        from tf_operator_tpu.models.resnet import ResNet18, ResNet50, init_resnet

        classes = 1000
        model = (ResNet50 if args.model == "resnet50" else ResNet18)(
            num_classes=classes
        )

        def init_params(rng):
            params, batch_stats = init_resnet(
                model, rng, image_size=args.image_size, batch=2
            )
            return params, {"batch_stats": batch_stats}

        def make_batch(rng):
            kx, ky = jax.random.split(rng)
            return {
                "x": jax.random.normal(
                    kx, (args.batch, args.image_size, args.image_size, 3)
                ),
                "y": jax.random.randint(ky, (args.batch,), 0, classes),
            }

        def loss_fn(params, model_state, batch, rng):
            from tf_operator_tpu.data import staging as staging_lib

            x = batch["x"]
            if x.dtype == jnp.uint8:
                # Real pipelines ship uint8 pixels (4x less host->device
                # transfer than f32); normalize on device where it fuses
                # into the first conv's input read. The --data-dir path
                # normalizes in the step's preprocess hook with the SAME
                # helper, so this branch only fires for direct callers
                # handing the loss raw uint8 batches.
                x = staging_lib.normalize_uint8(x)
            logits, mut = model.apply(
                {"params": params, **model_state}, x, train=True,
                mutable=["batch_stats"],
            )
            return M.cross_entropy_loss(logits, batch["y"]), dict(mut)

    elif args.model in ("bert-base", "bert-tiny"):
        from tf_operator_tpu.models import transformer as tfm

        base = tfm.BERT_BASE if args.model == "bert-base" else tfm.TINY
        cfg = tfm.TransformerConfig(
            vocab_size=base.vocab_size, num_layers=base.num_layers,
            hidden=base.hidden, num_heads=base.num_heads,
            max_len=max(args.seq, 8), causal=False,
        )
        attn = make_attention_fn(mesh, causal=False)
        model = tfm.BertMLM(cfg, attn_fn=attn)

        def init_params(rng):
            return tfm.BertMLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.TRANSFORMER_TP_RULES

        def make_batch(rng):
            return tfm.make_mlm_batch(rng, args.batch, args.seq, cfg.vocab_size)

        def loss_fn(params, model_state, batch, rng):
            logits = model.apply({"params": params}, batch["tokens"])
            return (
                tfm.mlm_loss(logits, batch["targets"], batch["mask"]),
                model_state,
            )

    elif args.model == "moe-lm":
        from tf_operator_tpu.models import moe as moe_lib

        cfg = moe_lib.MoEConfig(
            vocab_size=32000, num_layers=args.layers, hidden=args.hidden,
            num_heads=args.heads, max_len=args.seq, num_experts=8, top_k=2,
            moe_every=2, dispatch=args.moe_dispatch,
        )
        attn = make_attention_fn(mesh, causal=True)
        model = moe_lib.MoETransformerLM(cfg, attn_fn=attn)

        def init_params(rng):
            return moe_lib.MoETransformerLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.MOE_RULES

        def make_batch(rng):
            return {
                "tokens": jax.random.randint(
                    rng, (args.batch, args.seq), 0, cfg.vocab_size
                )
            }

        # Same per-device logits-bytes cutover as transformer-lm: chunking
        # exists for memory, not speed — measured on-chip at the bench
        # shape (seq 2048) the scanned head LOSES ~2% (chunk 1024) to ~17%
        # (chunk 512) vs the full-logits path, which XLA epilogue-fuses.
        moe_chunked = _logits_bytes(args, mesh, cfg.vocab_size) >= 6e9

        def loss_fn(params, model_state, batch, rng):
            return (
                moe_lib.moe_lm_loss(model, params, batch["tokens"],
                                    chunked=moe_chunked),
                model_state,
            )

    else:  # transformer-lm
        from tf_operator_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=32000, num_layers=args.layers, hidden=args.hidden,
            num_heads=args.heads, max_len=args.seq, causal=True,
            # --remat also remats per layer: at seq 64k the saved per-layer
            # intermediates alone exceed the chip (models/transformer.py
            # remat_layers note) — this is what makes 64k trainable.
            remat_layers=args.remat,
            # Selective policy: keep the flash (o, lse) residuals so the
            # backward never replays the O(T^2) kernel. Fits single-chip
            # 64k since the chunked-CE fix freed the stacked-logits
            # residuals (0.59 MFU, the bench config); sp-sharded
            # multi-chip jobs benefit even more (T/n-sized residuals).
            remat_save_flash=args.remat_save_flash,
            # Layer-subset middle ground: first K layers keep their flash
            # residuals (~100-200 MB each), dialing memory->speed where
            # saving all layers still OOMs (128k: cliff at K=10).
            remat_save_flash_layers=args.remat_save_flash_layers,
        )
        attn = make_attention_fn(mesh, causal=True)
        model = tfm.TransformerLM(cfg, attn_fn=attn)

        def init_params(rng):
            return tfm.TransformerLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.TRANSFORMER_TP_RULES

        def make_batch(rng):
            return {
                "tokens": jax.random.randint(
                    rng, (args.batch, args.seq), 0, cfg.vocab_size
                )
            }

        # When the full [B, T, vocab] f32 logits tensor gets big it (not
        # the activations) is the HBM peak: compute the head + softmax per
        # sequence chunk instead (numerics identical; see lm_loss_chunked).
        # Cutover on PER-DEVICE logits BYTES — batch scales the tensor
        # exactly like seq, but the batch dim is dp/fsdp-sharded, so the
        # global batch is divided by those axes first. Below the threshold
        # the one-shot head is measurably faster than the scan
        # (docs/perf.md): ~6 GB keeps every 4.2 GB case (8k b4, 16k b2,
        # 32k b1 single-chip) on the fast path on a 15.75 GB chip.
        chunked_loss = _logits_bytes(args, mesh, cfg.vocab_size) >= 6e9

        def loss_fn(params, model_state, batch, rng):
            if chunked_loss:
                h = model.apply(
                    {"params": params}, batch["tokens"], method="hidden"
                )
                loss = tfm.lm_loss_chunked(
                    h, params["lm_head"]["kernel"], batch["tokens"]
                )
                return loss, model_state
            logits = model.apply({"params": params}, batch["tokens"])
            return tfm.lm_loss(logits, batch["tokens"]), model_state

    if args.eval:
        import numpy as np

        # The evaluator only needs a host-side restore template (shapes +
        # dtypes) — never pay a device init for it.
        abstract_p, _ = jax.eval_shape(init_params, jax.random.key(0))
        template = jax.tree.map(
            lambda s: np.zeros(s.shape, s.dtype), abstract_p
        )
        rc = _run_evaluator(args, model, template, make_batch, loss_fn,
                            guard)
        # The evaluator records eval + checkpoint/restore spans; export
        # them on every exit path (timeout included — rc != 0 traces are
        # the interesting ones).
        _maybe_export_trace(args)
        return rc

    # Single-writer semantics differ by runtime shape. Independent
    # processes (PS-strategy: each worker is its own jax runtime): only the
    # chief/worker-0 touches the shared dir. ONE multi-process runtime
    # (jax.distributed): EVERY process must enter the save — orbax runs
    # multihost sync barriers inside save(), and a single process calling it
    # deadlocks against the others' next collective (orbax itself writes
    # from process 0 only).
    saver = args.checkpoint_dir and (
        _is_checkpoint_writer() or jax.process_count() > 1
    )

    if args.checkpoint_dir and jax.process_index() == 0 \
            and _is_checkpoint_writer():
        # A preempt/retry loop strands orbax tmp dirs (a save killed before
        # its rename) in the shared dir; sweep them before resume so disk
        # stops leaking one partial checkpoint per kill.
        from tf_operator_tpu.models import checkpoint as _ckpt_sweep

        swept = _ckpt_sweep.sweep_tmp_dirs(args.checkpoint_dir)
        if swept:
            _emit({"event": "checkpoint_tmp_swept", "entries": swept})

    from tf_operator_tpu import optim as optim_lib

    # Dtype-configurable Adam/AdamW (tf_operator_tpu/optim.py): the default
    # f32/no-master config is leaf-for-leaf checkpoint-compatible with the
    # optax.adamw state earlier rounds wrote, and parity-pinned against
    # optax by tests/test_optimizer.py.
    tx = optim_lib.make_optimizer(optim_lib.OptimizerConfig(
        name=args.optimizer,
        learning_rate=args.lr,
        moment_dtype=args.moment_dtype,
        master_weights=args.master_weights,
    ))

    def build_state():
        p, ms = init_params(jax.random.key(0))
        return create_train_state(p, tx, ms)

    # One compiled program builds the fully-sharded initial state directly
    # on the mesh: out_shardings come from an eval_shape pass, so setup
    # costs a single compile+dispatch instead of one round-trip per
    # init/optimizer primitive (which dominated cold start on a tunneled
    # chip) — and params materialize already laid out, never replicated.
    st_sh = state_shardings(jax.eval_shape(build_state), mesh, rules)
    state = jax.jit(build_state, out_shardings=st_sh)()
    state, start_step = _try_resume(
        args.checkpoint_dir, state, tx, mesh=mesh,
        allow_reshape=allow_reshape,
    )
    # Shard-by-spec placement: the (possibly resharded) host tree lands
    # on the CURRENT mesh per the sharding rules — params and optimizer
    # state re-laid-out together, whatever shape the checkpoint came from.
    state = shard_state(state, mesh, rules)
    _emit({"event": "model_ready", "t": time.time()})
    # Startup liveness milestone: the resumed step is known, the first
    # (possibly long) compile is about to start — refresh the heartbeat so
    # the watchdog's staleness clock restarts here, not at process start.
    _hb(start_step, force=True)
    if start_step >= args.steps:
        # Already trained to (or past) the target: restart policies must be
        # idempotent, not retrain.
        from tf_operator_tpu.models import checkpoint as ckpt_lib

        if (saver and jax.process_index() == 0 and start_step > 0
                and ckpt_lib.final_step(args.checkpoint_dir) is None):
            ckpt_lib.mark_final(args.checkpoint_dir, start_step)
        _emit({"event": "done", "t": time.time(), "steps": start_step,
               "steady_steps_per_sec": None, "examples_per_sec": None,
               "final_loss": None, "total_s": round(time.time() - t_start, 3),
               "resumed_complete": True})
        from tf_operator_tpu.parallel.distributed import distributed_goodbye

        distributed_goodbye()
        return 0
    xla_options = dict(kv.split("=", 1) for kv in args.xla_option)
    if (args.model == "moe-lm" and args.moe_dispatch == "sparse"
            and jax.default_backend() == "tpu"):
        # lax.ragged_dot's mosaic kernel at the bench expert shapes picks a
        # 4096x768x512 tiling: ~21.5M scoped VMEM for the forward and
        # ~33.8M for the dW ragged-dot in the backward; the 16M default
        # fails the compile outright. 48M covers both with margin.
        xla_options.setdefault("xla_tpu_scoped_vmem_limit_kib", "49152")
    if args.data_dir:
        return _train_on_dataset(args, state, start_step, loss_fn, tx, mesh,
                                 rules, saver, t_start, guard,
                                 xla_options=xla_options or None)

    compile_scanned = make_scanned_train_step(
        loss_fn, tx, mesh, make_batch, rules=rules, remat=args.remat,
        compiler_options=xla_options or None,
    )
    # Chunked on-device loop: one dispatch per `chunk` steps (batches are
    # generated inside the compiled program) — per-step host round-trips to
    # a tunneled chip otherwise dominate small-model step time. The chunk
    # honors the checkpoint cadence EXACTLY (gcd, so chunk boundaries land
    # on every multiple of checkpoint_every even when log_every doesn't
    # divide it). RNG streams key off the GLOBAL step, so a resumed run
    # reproduces the uninterrupted trajectory.
    import math

    # Chunk derives from flags only (identical on every replica): gating on
    # the local checkpoint-writer role would give chief and workers
    # different scan unrolls — divergent SPMD programs across one
    # jax.distributed job.
    chunk = max(1, min(args.log_every, args.steps - start_step))
    if args.checkpoint_dir and args.checkpoint_every:
        chunk = max(1, math.gcd(chunk, args.checkpoint_every))
    step_chunk = compile_scanned(state, chunk)
    ckpt_marks = (start_step // args.checkpoint_every) if args.checkpoint_every else 0
    last_save_s, last_ckpt_step = 0.0, -1

    def maybe_checkpoint(done: int, st=None) -> None:
        nonlocal ckpt_marks, last_save_s, last_ckpt_step
        if not (saver and args.checkpoint_every) or done >= args.steps:
            return  # the final save (marked FINAL) happens after the loop
        marks = done // args.checkpoint_every
        if marks > ckpt_marks:
            ckpt_marks = marks
            if st is not None:
                # The phase opens only around an ACTUAL save: timing the
                # no-op calls too would report a nonzero checkpoint phase
                # for runs that never saved in the window.
                with st.phase("checkpoint"):
                    last_save_s = _save_checkpoint(
                        args.checkpoint_dir, done, state,
                        keep=args.keep_checkpoints)
            else:
                last_save_s = _save_checkpoint(
                    args.checkpoint_dir, done, state,
                    keep=args.keep_checkpoints)
            last_ckpt_step = done

    def check_boundary(done: int, st=None) -> int | None:
        """Heartbeat + chaos hang/kill-at-step + preemption handling after
        a chunk: returns the exit code to leave with, or None to continue
        training."""
        _hb(done)
        _boundary_chaos(done, start_step)
        if guard.triggered:
            return _preempt_exit(args, guard, state, done, saver,
                                 last_save_s, last_ckpt_step, st)
        return None

    state, metrics = step_chunk(state)
    # Host transfer, not block_until_ready (a no-op through the axon
    # tunnel): startup_s must include the first chunk's device execution.
    first_loss = float(metrics["loss"])
    t_first = time.time()
    done = start_step + chunk
    _emit(
        {
            "event": "first_step",
            "t": t_first,
            "startup_s": round(t_first - t_start, 3),
            "steps_in_first_call": chunk,
            "loss": first_loss,
            "mesh": dict(mesh.shape),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": len(jax.devices()),
        }
    )
    maybe_checkpoint(done)
    rc = check_boundary(done)
    if rc is not None:
        return rc

    # Steady-state window: full chunks only (every dispatch reuses the one
    # compiled program). The tail chunk, if any, needs its own compile and
    # runs AFTER dt is captured so compilation never pollutes throughput.
    full_chunks = (args.steps - done) // chunk
    tail = (args.steps - done) % chunk
    profiling = bool(args.profile_dir) and full_chunks > 0
    # Tracing adds host/device overhead, so the profiled chunk must sit
    # OUTSIDE the throughput window: with >=2 full chunks, time the first
    # n-1 untraced and trace only the last; with a single chunk the trace
    # covers it and the throughput is marked as measured-under-profiling.
    profile_last_chunk = profiling and full_chunks >= 2
    timed_chunks = full_chunks - 1 if profile_last_chunk else full_chunks
    if profiling and not profile_last_chunk:
        _start_profile(args.profile_dir)
    # Latency-hiding progress: fetching a chunk's loss right after
    # dispatching it idles the chip for a full host<->device round trip
    # (~100 ms through the axon tunnel) every chunk. Instead, dispatch
    # chunk i+1 FIRST (donated state returns immediately as a future),
    # then fetch chunk i's loss while i+1 computes — the transfer rides
    # under compute and only the window-closing fetch blocks. Progress
    # events lag one chunk; each carries its own step number.
    # Phase accounting at chunk granularity: one dispatch covers `chunk`
    # steps, so each chunk records ONE sample weighted as `chunk` per-step
    # samples (telemetry/phases.py) — the done event's step_time_s stays a
    # per-STEP distribution whatever the dispatch granularity.
    t0 = time.time()
    pending = None  # (step count at fetch, metrics of that chunk)
    acct = telemetry.make_step_accounting()
    for _ in range(timed_chunks):
        _trace_window_check(args, done - start_step - chunk)
        with acct.step(done + chunk, n_steps=chunk) as st:
            with st.phase("dispatch"):
                state, metrics = step_chunk(state)
            done += chunk
            if pending is not None:
                pstep, pmetrics = pending
                # Throttle to the requested cadence: emitting every
                # sub-log_every chunk would reintroduce per-step round-trips.
                if pstep % args.log_every == 0:
                    with st.phase("device_blocked"):
                        ploss = float(pmetrics["loss"])
                    _emit({"event": "progress", "step": pstep, "loss": ploss})
            pending = (done, metrics)
            maybe_checkpoint(done, st)
            rc = check_boundary(done, st)
            if rc is not None:
                return rc
    if pending is not None:
        # The last chunk's fetch is the REAL window closure —
        # block_until_ready is a no-op through the axon tunnel.
        pstep, pmetrics = pending
        closing_loss = float(pmetrics["loss"])
    dt = time.time() - t0
    if pending is not None and (pstep % args.log_every == 0
                                or pstep == args.steps):
        _emit({"event": "progress", "step": pstep, "loss": closing_loss})
    steady = timed_chunks * chunk
    if profile_last_chunk:
        _start_profile(args.profile_dir)
    if profiling and not profile_last_chunk:
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": steady, "in_timed_window": True})
    if profile_last_chunk:
        state, metrics = step_chunk(state)
        done += chunk
        # Host transfer BEFORE stop_trace: block_until_ready is a no-op
        # through the axon tunnel, and stopping the trace while the chunk
        # is still executing would truncate it.
        chunk_loss = float(metrics["loss"])
        if done % args.log_every == 0 or done == args.steps:
            _emit({"event": "progress", "step": done, "loss": chunk_loss})
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": chunk, "in_timed_window": False})
        maybe_checkpoint(done)
        rc = check_boundary(done)
        if rc is not None:
            return rc

    if tail:
        state, metrics = compile_scanned(state, tail)(state)
        done += tail
        _emit({"event": "progress", "step": done,
               "loss": float(metrics["loss"])})
    if saver:
        _save_checkpoint(args.checkpoint_dir, args.steps, state, final=True,
                         keep=args.keep_checkpoints)
    # The final step must land in the heartbeat whatever the throttle did
    # at intermediate boundaries (the watchdog/collector read it back).
    _hb(args.steps, force=True)
    # With steps <= one chunk there is no steady-state window (only the
    # compile call ran); report null throughput rather than a
    # microseconds-denominator lie.
    sps = round(steady / dt, 4) if steady > 0 else None
    telem = acct.summary()
    _emit(
        {
            "event": "done",
            "t": time.time(),
            "steps": args.steps,
            "steady_steps_per_sec": sps,
            "examples_per_sec": round(steady * args.batch / dt, 4) if steady > 0 else None,  # 4 dp: 2-dp quantized batch-1 long-context rows by +-2.6%
            "final_loss": float(metrics["loss"]),
            "total_s": round(time.time() - t_start, 3),
            # Per-step distribution + telescoping phase breakdown over the
            # steady window (telemetry/phases.py); None when the run had
            # no steady chunks, same rule as steady_steps_per_sec.
            "step_time_s": telem["step_time_s"] if telem else None,
            "phase_breakdown": telem["phase_breakdown"] if telem else None,
        }
    )
    _maybe_export_trace(args)
    # Synchronized multi-process exit (no-op single-process): see
    # parallel.distributed.distributed_goodbye.
    from tf_operator_tpu.parallel.distributed import distributed_goodbye

    distributed_goodbye()
    return 0


if __name__ == "__main__":
    sys.exit(main())
