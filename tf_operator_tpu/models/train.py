"""Generic trainer — the workload binary TrainJob pods run.

This is the data-plane entrypoint the operator's pods execute (the role
dist_mnist.py / keras_model_to_estimator.py played in the reference's
examples, SURVEY.md §3.4), TPU-native:

  python -m tf_operator_tpu.models.train --model resnet50 --steps 100

  1. jax.distributed from the operator-injected env (multi-process jobs)
  2. Mesh from TPUJOB_MESH (dp/fsdp/tp/sp axes)
  3. jitted SPMD train step (bf16 compute, donated state)
  4. synthetic data by default (bench determinism); progress as JSON lines
     on stdout and, when TPUJOB_METRICS_FILE is set, appended to that file
     (the hook bench.py uses to time startup->first-step and steps/sec).
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import sys
import threading
import time
from typing import Any

# Stdlib-only (tracer + phase accounting): safe before the jax import and
# cheap enough that the disabled path costs one attribute read per call.
from tf_operator_tpu import telemetry

# The metrics stream has two producers since async checkpointing: the
# step loop and the ckpt-writer thread (checkpoint/checkpoint_pruned
# events ride the write leg). The lock keeps whole lines whole.
_emit_lock = threading.Lock()


def _emit(event: dict) -> None:
    line = json.dumps(event)
    with _emit_lock:
        print(line, flush=True)
        path = os.environ.get("TPUJOB_METRICS_FILE")
        if path:
            with open(path, "a") as f:
                f.write(line + "\n")


def _start_profile(profile_dir: str) -> None:
    """Start an XProf device trace under profile_dir/<replica rank>.

    Replica type+index is unique per pod in every regime (chief-0 and
    worker-0 differ by type; non-distributed local pods have no distinct
    jax.process_index()). The reference delegated all profiling to
    cAdvisor/Prometheus node metrics (SURVEY.md §5); this is the TPU-native
    equivalent: per-op XProf timelines.
    """
    import jax

    rank = (f"{os.environ.get('TPUJOB_REPLICA_TYPE') or 'local'}-"
            f"{os.environ.get('TPUJOB_REPLICA_INDEX', '0')}")
    trace_dir = os.path.join(profile_dir, rank)
    jax.profiler.start_trace(trace_dir)
    _emit({"event": "profile_start", "dir": trace_dir})


def _trace_rank() -> str:
    """Replica identity for per-pod trace files — same naming as the
    jax.profiler dirs (_start_profile), so the two trace kinds pair up."""
    return (f"{os.environ.get('TPUJOB_REPLICA_TYPE') or 'local'}-"
            f"{os.environ.get('TPUJOB_REPLICA_INDEX', '0')}")


def _trace_window_check(args, steps_done: int) -> None:
    """Close the --trace-steps window: once N steps are recorded the
    tracer disables, so the rest of a long run costs nothing and the ring
    holds the WINDOW, not the last `capacity` events of the tail."""
    if args.trace and args.trace_steps and steps_done >= args.trace_steps:
        telemetry.get_tracer().enabled = False


def _maybe_export_trace(args) -> None:
    """Write the Chrome trace-event JSON (load it in Perfetto or
    chrome://tracing) and emit trace_done with its path."""
    if not getattr(args, "trace", False):
        return
    tracer = telemetry.get_tracer()
    tracer.enabled = False  # export is not part of the trace
    path = os.path.join(args.trace_dir or "traces",
                        f"{_trace_rank()}.trace.json")
    n = tracer.export(path)
    _emit({"event": "trace_done", "path": path, "events": n,
           "dropped_events": tracer.dropped_events})


def _is_checkpoint_writer() -> bool:
    """Chief (or worker-0 when no chief exists) writes checkpoints — the same
    role the reference gave worker-0/chief for summaries (SURVEY.md §3.4).
    A standalone run (no operator env) always writes."""
    rtype = os.environ.get("TPUJOB_REPLICA_TYPE", "").lower()
    if not rtype:
        return True
    if rtype in ("chief", "master"):
        return True
    if rtype != "worker" or os.environ.get("TPUJOB_REPLICA_INDEX", "0") != "0":
        return False
    # Worker-0 writes only when the job has no chief/master (one writer per
    # checkpoint dir); the injected ClusterSpec says whether one exists.
    try:
        cluster = json.loads(os.environ.get("TF_CONFIG", "{}")).get("cluster", {})
    except ValueError:
        cluster = {}
    return not ("chief" in cluster or "master" in cluster)


def _aux_tree(state) -> dict:
    """Resume payload beyond params (optimizer moments + f32 master copy,
    step counter, mutable model state). The optimizer state is stored as a
    flat leaf list — orbax does not round-trip namedtuple structure (tuples
    come back as lists) — and the resume side rebuilds it with the
    freshly-initialized state's treedef. Leaves keep their configured
    dtypes (bf16 moments save/restore as bf16; the f32 master as f32)."""
    import jax

    tree = {
        "step": state.step,
        "opt_leaves": list(jax.tree.leaves(state.opt_state)),
    }
    if state.model_state:
        tree["model_state"] = state.model_state
    return tree


# Trainer-side chaos directives (kill-at-step / hang-at-step /
# torn-checkpoint), set once per main() from TPUJOB_CHAOS / --chaos; None —
# the default — costs one `is None` check per boundary.
_chaos = None

# Progress heartbeat (TPUJOB_HEARTBEAT_FILE, runtime-injected): written at
# step boundaries so the operator's hang watchdog can tell a Running job
# from a wedged one. Module-global like _chaos (the two loops and the
# boundary helpers share it); None-path costs one `is None` check.
_heartbeat = None

# The live mesh, for the checkpoint sharding manifest (every save records
# the gang shape + per-leaf layout it was taken from, so a restore onto a
# DIFFERENT shape can reshard instead of guessing). Module-global like
# _chaos/_heartbeat: _save_checkpoint has ~6 call sites across both loops
# and the preemption path.
_mesh = None

# Whether saves also record the crc32 digest (the reshard bit-equality
# witness). PR 9 made this opt-in because the two full-tree passes ran on
# the step loop's critical path; on the async write leg they ride the
# writer thread instead, so digests are default-ON whenever async
# checkpointing is active (and, as before, whenever the job opted into
# reshaping — elastic jobs need the witness even under --checkpoint-mode
# sync). The sharding manifest itself is cheap and always written.
_digest_saves = False

# The async checkpoint writer (None = --checkpoint-mode sync, or no
# checkpoint dir). Module-global like _chaos/_heartbeat/_mesh: the save
# path has ~6 call sites across both loops and the preemption teardown.
_ckpt_writer: "_CkptWriter | None" = None

# Sync-mode counterpart of the writer's accounting, so the done event's
# `checkpoint` block exists in both modes (hidden_fraction is 0.0 by
# definition when every save blocks the loop). Only the main thread
# writes it, but the module hosts real threads now — locked on principle
# (and to keep tpulint's unlocked-state pass honest).
_sync_ckpt_stats = {"saves": 0, "snapshot_s": 0.0, "write_s": 0.0}
_sync_ckpt_lock = threading.Lock()


def _hb(step: int, force: bool = False) -> None:
    if _heartbeat is not None:
        _heartbeat.write(step, force=force)


def _boundary_chaos(done: int, start_step: int) -> None:
    """Step-boundary chaos hook shared by both loops: hang-at-step (stop
    making progress without exiting — the wedged-collective simulation the
    heartbeat watchdog exists for), then kill-at-step. Order matters: a
    directive pairing both at one step should go quiet BEFORE dying."""
    if _chaos is None:
        return
    d = _chaos.hang_at(done, start_step)
    if d is not None:
        from tf_operator_tpu import chaos as chaos_lib

        duration = d.params.get("duration")
        _emit({"event": "chaos_hang", "step": done, "duration": duration})
        chaos_lib.hang(duration)
    _chaos.maybe_kill(done, start_step)


@dataclasses.dataclass
class _SaveItem:
    """One checkpoint save, fully detached from the device: host copies
    of both trees plus everything the write leg needs that must be read
    from LIVE state (sharding layouts, mesh shape) — captured in the
    blocking snapshot leg so the writer thread never touches a device
    tree (or anything else that could dispatch XLA)."""

    ckpt_dir: str
    step: int
    host_params: Any
    host_aux: Any
    info: dict
    final: bool
    keep: int


def _snapshot_state(ckpt_dir: str, step: int, state, final: bool,
                    keep: int, copy_leaves: bool = True) -> _SaveItem:
    """Blocking snapshot leg: device->host copy of params + optimizer
    state at a step boundary (the only part of a save that must observe a
    consistent tree) plus the sharding-manifest payload read off the live
    leaves. With copy_leaves (the async path) every leaf OWNS its bytes —
    the step loop is free to donate/mutate the device state the moment
    this returns; a sync save serializes inline before any further
    dispatch, so it skips the defensive memcpy."""
    import jax

    from tf_operator_tpu.models import checkpoint as ckpt
    from tf_operator_tpu.parallel import mesh as mesh_lib

    import numpy as np

    def owned_host_copy(tree):
        """device_get + ensure every leaf OWNS its bytes. On the CPU
        backend device_get returns numpy VIEWS aliasing the live device
        buffers; with donated train state the next dispatched chunk then
        overwrites the 'snapshot' in place before the writer thread
        serializes it (observed: a trainstate_8 whose step read 12 —
        same aliasing family as restore_named's mandatory-copy rule).
        Leaves that already own their data (real D2H copies on TPU) pass
        through without a second memcpy."""
        def own(leaf):
            arr = np.asarray(leaf)
            return arr if arr.flags.owndata else arr.copy()

        return jax.tree.map(own, jax.device_get(tree))

    host_of = owned_host_copy if copy_leaves else jax.device_get
    aux = _aux_tree(state)
    host_aux = host_of(aux)
    host_params = host_of(state.params)
    info = {
        "processCount": jax.process_count(),
        "deviceCount": jax.device_count(),
        "mesh": (mesh_lib.shape_dict(_mesh)
                 if _mesh is not None else {}),
        "leaves": ckpt.leaf_shardings(state.params),
        "auxLeaves": ckpt.leaf_shardings(aux),
    }
    return _SaveItem(ckpt_dir=ckpt_dir, step=step, host_params=host_params,
                     host_aux=host_aux, info=info, final=final, keep=keep)


def _write_snapshot(item: _SaveItem) -> None:
    """Write leg: serialize the host snapshot to orbax, publish it
    (tmp->rename discipline in checkpoint.save_named, so the PR 4
    backward resume walk is untouched), write census + sharding manifests
    and digests, run retention pruning, and only THEN force the heartbeat
    — the PR 9 durable-progress rule keys on write COMPLETION, never on
    save initiation. Runs on the ckpt-writer thread in async mode and
    inline in sync mode; it must never dispatch an XLA program (tpulint
    TPT201 roots the writer thread here — same invariant as the PR 2
    transfer threads): everything below is host numpy, file IO, and (in
    multi-process runtimes) orbax's gRPC-client barriers."""
    import jax

    from tf_operator_tpu.models import checkpoint as ckpt

    with telemetry.span("checkpoint/ckpt_write", step=item.step,
                        final=item.final):
        # trainstate first, so any visible step_<N> has its resume
        # payload beside it (the historical aux-before-params order).
        ckpt.save_named(item.ckpt_dir, f"trainstate_{item.step}",
                        item.host_aux)
        path = ckpt.save(item.ckpt_dir, item.step, item.host_params)
        # orbax coordinates the collective save, but mark_final/_emit/
        # prune are plain file IO: one writer only, or concurrent
        # os.replace of the shared .FINAL.tmp races (loser raises,
        # failing a finished job).
        if jax.process_index() == 0:
            info = dict(item.info)
            if _digest_saves:
                # crc32 of the host bytes — the bit-equality witness the
                # resumed event reports back. On the async leg these two
                # full-tree passes ride the writer thread, hidden behind
                # training (why digests could flip back to default-on).
                info["digest"] = {
                    "params": ckpt.tree_digest(item.host_params),
                    "trainstate": ckpt.tree_digest(item.host_aux),
                }
            ckpt.write_sharding_manifest(item.ckpt_dir,
                                         f"step_{item.step}", info)
            if item.final:
                ckpt.mark_final(item.ckpt_dir, item.step)
            _emit({"event": "checkpoint", "step": item.step, "path": path,
                   "final": item.final})
            if item.keep:
                pruned = ckpt.prune_checkpoints(item.ckpt_dir, item.keep)
                if pruned:
                    _emit({"event": "checkpoint_pruned", "steps": pruned,
                           "keep": item.keep})
            # Single read of the module global: the main thread's finally
            # nulls _chaos only after close() drains this leg, but a
            # local binding keeps even a future reordering from turning
            # the check-then-use into a writer-thread AttributeError.
            chaos = _chaos
            if chaos is not None:
                torn = chaos.tear_for_step(item.step)
                if torn is not None:
                    from tf_operator_tpu import chaos as chaos_lib

                    chaos.state.mark(torn)
                    damaged = chaos_lib.tear_checkpoint(
                        item.ckpt_dir, item.step,
                        torn.params.get("mode", "truncate")
                    )
                    _emit({"event": "chaos_torn_checkpoint",
                           "step": item.step, "path": damaged})
    # A DURABLE save is progress: force the heartbeat past the 2 Hz
    # throttle so the operator (hang watchdog, chaos at_step directives,
    # the PR 5 tally-reset baseline) sees the checkpointed step promptly
    # — and never a step whose checkpoint a crash could still erase
    # (HeartbeatWriter is thread-safe + step-monotonic, so a write leg
    # finishing behind the boundary heartbeats only refreshes t).
    _hb(item.step, force=True)


def _warm_checkpointer() -> None:
    """Build the process's cached orbax Checkpointer ahead of the first
    save: its construction costs about as much as a small tree's whole
    write, and paying it lazily would sit exactly in the window between
    a save's submit and a preemption/kill that decides whether the save
    survives (the gang-kill e2es race that window against the runtime's
    drain-grace SIGKILL). Runs on the writer thread at startup — off the
    step loop AND off the first save. Best-effort: a broken backend
    surfaces on the real save, with context."""
    from tf_operator_tpu.models import checkpoint as ckpt

    try:
        ckpt._checkpointer()
    except Exception as e:  # noqa: BLE001 — the real save reports it properly
        print(f"warning: checkpointer warm-up failed "
              f"({type(e).__name__}: {e}); the first save will rebuild it "
              f"and surface any real error", file=sys.stderr)


def _ckpt_writer_main(writer: "_CkptWriter") -> None:
    """ckpt-writer thread body: warm the checkpointer, then drain the
    single-slot queue, timing each write leg. First failure is latched
    and the thread exits — the next submit/drain re-raises it on the
    step loop, preserving sync-mode crash semantics for broken
    storage."""
    _warm_checkpointer()
    while True:
        with writer._cond:
            while writer._item is None and not writer._stop:
                writer._cond.wait()
            if writer._item is None:
                return  # stopped with an empty slot
            item = writer._item
        try:
            t0 = time.monotonic()
            _write_snapshot(item)
            dt = time.monotonic() - t0
        except BaseException as e:  # noqa: BLE001 — latched + re-raised
            with writer._cond:
                writer._error = e
                writer._item = None
                writer._cond.notify_all()
            return
        with writer._cond:
            writer.write_s += dt
            writer.saves += 1
            writer.last_step = item.step
            writer._item = None
            writer._cond.notify_all()


class _CkptWriter:
    """Single-slot async checkpoint write pipeline.

    Exactly ONE save may be in flight: submit() of the next save blocks
    (backpressure) until the previous write leg drains — two concurrent
    orbax writes would contend for disk and, multi-process, interleave
    their barrier sequences. The slot + condition variable make the
    discipline structural rather than advisory; `drains`/`drain_wait_s`
    record how often and how long the step loop actually waited, which is
    exactly the VISIBLE share of write time (hidden_fraction's
    denominator-complement in the done event)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._item: _SaveItem | None = None
        self._stop = False
        self._error: BaseException | None = None
        self.last_step: int | None = None  # newest DURABLE step
        self.saves = 0
        self.write_s = 0.0
        self.snapshot_s = 0.0
        self.drains = 0          # submits that hit backpressure
        self.drain_wait_s = 0.0  # seconds the step loop blocked on them
        # Started eagerly (not at first submit) so the thread's
        # checkpointer warm-up overlaps model build/compile instead of
        # delaying the first save. Callers construct the writer post-fork
        # (in _run_trainer), so the thread never crosses a fork.
        self._thread = threading.Thread(
            target=_ckpt_writer_main, args=(self,),
            name="ckpt-writer", daemon=True,
        )
        self._thread.start()

    @property
    def error(self) -> BaseException | None:
        with self._cond:
            return self._error

    def _raise_pending(self) -> None:
        if self._error is not None:
            raise RuntimeError(
                f"async checkpoint write failed: "
                f"{type(self._error).__name__}: {self._error}"
            ) from self._error

    def submit(self, item: _SaveItem) -> None:
        """Hand a snapshot to the writer; blocks while the previous save
        is still writing (the backpressure leg of the snapshot phase)."""
        with self._cond:
            self._raise_pending()
            if self._item is not None:
                self.drains += 1
                t0 = time.monotonic()
                while self._item is not None and self._error is None:
                    self._cond.wait()
                self.drain_wait_s += time.monotonic() - t0
                self._raise_pending()
            self._item = item
            self._cond.notify_all()

    def drain(self, raise_error: bool = True) -> float:
        """Block until no write is queued or in flight; returns seconds
        waited (NOT counted into drain_wait_s — the final-save and
        preemption drains stall job teardown, not the step loop)."""
        t0 = time.monotonic()
        with self._cond:
            while self._item is not None and self._error is None:
                self._cond.wait()
            if raise_error:
                self._raise_pending()
        return time.monotonic() - t0

    def mean_write_s(self) -> float:
        with self._cond:
            return self.write_s / self.saves if self.saves else 0.0

    def mean_save_s(self) -> float:
        """Mean FULL save cost (snapshot + write) over completed saves —
        what a synchronous emergency save is expected to cost."""
        with self._cond:
            if not self.saves:
                return 0.0
            return (self.snapshot_s + self.write_s) / self.saves

    def note_snapshot(self, seconds: float) -> None:
        with self._cond:
            self.snapshot_s += seconds

    def stats(self) -> dict:
        with self._cond:
            hidden = (max(0.0, 1.0 - self.drain_wait_s / self.write_s)
                      if self.write_s > 0 else None)
            return {
                "mode": "async",
                "saves": self.saves,
                "snapshot_s": round(self.snapshot_s, 6),
                "write_s": round(self.write_s, 6),
                "drains": self.drains,
                "drain_wait_s": round(self.drain_wait_s, 6),
                "hidden_fraction": (round(hidden, 4)
                                    if hidden is not None else None),
            }

    def close(self) -> None:
        """Cleanup-path teardown: wait out any in-flight write (stranding
        it mid-publish on a NON-fatal exit would tear nothing, but why
        risk the disk churn), stop the thread, swallow latched errors —
        the normal paths already re-raised them at submit/drain time."""
        self.drain(raise_error=False)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=60.0)


def _ckpt_done_stats() -> dict | None:
    """The done event's `checkpoint` block, whatever the mode (None when
    the run never saved)."""
    if _ckpt_writer is not None:
        return _ckpt_writer.stats()
    with _sync_ckpt_lock:
        s = dict(_sync_ckpt_stats)
    if not s["saves"]:
        return None
    return {
        "mode": "sync",
        "saves": s["saves"],
        "snapshot_s": round(s["snapshot_s"], 6),
        "write_s": round(s["write_s"], 6),
        "drains": 0,
        "drain_wait_s": 0.0,
        "hidden_fraction": 0.0,
    }


def _save_checkpoint(ckpt_dir: str, step: int, state, final: bool = False,
                     keep: int = 0, st=None, sync: bool = False) -> float:
    """step_<N> holds params ONLY (the evaluator/external contract —
    cheap to restore, format-compatible with hand-written checkpoints);
    trainstate_<N> holds the resume payload.

    Async mode (the default, when the writer exists): only the snapshot
    leg + any backpressure wait block the step loop (phase
    `ckpt_snapshot`); the write leg rides the ckpt-writer thread. A
    final=True save drains before returning — job completion is durable
    completion. Sync mode (--checkpoint-mode sync, or sync=True for the
    preemption fast path) runs both legs inline under the `checkpoint`
    phase, exactly the historical behavior.

    Returns the estimated wall-clock of a SYNCHRONOUS save (snapshot +
    write) — the preemption guard's estimate of what an emergency save
    will cost against the grace budget, whichever mode produced it."""
    writer = _ckpt_writer
    t0 = time.monotonic()
    if writer is None or sync:
        ctx = (st.phase("checkpoint") if st is not None
               else contextlib.nullcontext())
        with ctx:
            item = _snapshot_state(ckpt_dir, step, state, final, keep,
                                   copy_leaves=False)
            snap_s = time.monotonic() - t0
            _write_snapshot(item)
        total = time.monotonic() - t0
        with _sync_ckpt_lock:
            _sync_ckpt_stats["saves"] += 1
            _sync_ckpt_stats["snapshot_s"] += snap_s
            _sync_ckpt_stats["write_s"] += total - snap_s
        return total
    ctx = (st.phase("ckpt_snapshot") if st is not None
           else contextlib.nullcontext())
    with ctx:
        # The phase covers the whole blocking leg (snapshot + any
        # backpressure wait inside submit); the done block keeps the two
        # separable — snapshot_s is the irreducible per-save stall, the
        # writer's drain_wait_s is the backpressure the save interval
        # chose.
        item = _snapshot_state(ckpt_dir, step, state, final, keep)
        snap_s = time.monotonic() - t0
        writer.submit(item)
    writer.note_snapshot(snap_s)
    if final:
        # The end-of-run save must be durable before the trainer reports
        # done (FINAL marker, evaluator handoff, operator completion all
        # key on it).
        writer.drain()
    return snap_s + writer.mean_write_s()


def _try_resume(ckpt_dir: str | None, state, tx, mesh=None,
                allow_reshape: bool = False):
    """Restore the newest RESTORABLE checkpoint, if any. Returns
    (state, start_step).

    Topology portability: each checkpoint carries a sharding manifest
    (gang shape + per-leaf layout, written by _save_checkpoint). A
    candidate saved at a DIFFERENT shape (process count or mesh axis
    layout) is a FOREIGN-shape checkpoint: without `allow_reshape`
    (--allow-reshape / TPUJOB_ALLOW_RESHAPE) it degrades exactly like a
    corrupt one — skipped with a `resume_fallback` event, walk continues
    — never a crash. With the flag, restore RESHARDS: per-leaf global
    shapes are checked against the template first (a model-config change
    is a skip, not a guess), the host tree restores as usual, and the
    caller's shard_state lays every leaf out onto the CURRENT mesh by
    the sharding rules — params and optimizer state together. Leaves
    whose values depend on the gang size are re-derived, not restored:
    RNG streams key off the global step and the data loop's shard reader
    re-splits by the new process count. A checkpoint with NO sharding
    manifest (pre-manifest, hand-written) gets the census grace:
    restorable, but same-shape semantics only — with allow_reshape set,
    a resume_fallback event records that reshape verification was
    unavailable.
    The reference's contract was 'stable pod identity + restart semantics so
    TF can resume from its own checkpoints' (SURVEY.md §5); here the trainer
    itself resumes, so a pod restarted by the operator's restart policy
    continues the trajectory instead of starting over. A step_<N> without a
    trainstate_<N> (external/hand-written checkpoint) resumes params-only
    with a fresh optimizer.

    Torn-checkpoint hardening (the preemption scenario's second half): the
    walk goes BACKWARD through list_steps past steps whose manifest census
    fails (checkpoint.validate_step) or whose restore raises — each skip
    emits a `resume_fallback` event — so one corrupt latest checkpoint
    costs the steps since the previous valid one instead of turning a
    retryable failure into a permanent crash-loop. All-corrupt (and
    fresh-dir) degrade to a step-0 cold start with a warning.

    Mixed-precision state restores at each slab's CONFIGURED dtype (orbax
    casts to the restore template, so a legacy all-f32 trainstate also loads
    under a bf16-moment config). Params restore at the optimizer's master
    precision (f32 under master_weights — a legacy f32 step_<N> keeps its
    full precision, a new bf16 one upcasts exactly) and the bf16 compute
    copy is re-derived; on the params-only path under master_weights the
    optimizer re-inits from the RESTORED params so the f32 master matches
    the checkpoint, not the session's random init."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu import optim as optim_lib
    from tf_operator_tpu.models import checkpoint as ckpt
    from tf_operator_tpu.parallel.train_step import TrainState

    if not ckpt_dir:
        return state, 0
    all_steps = ckpt.list_steps(ckpt_dir)
    ordered = list(reversed(all_steps))  # newest first

    from tf_operator_tpu.parallel import mesh as mesh_lib

    cur_shape = {
        "processCount": jax.process_count(),
        "mesh": mesh_lib.shape_dict(mesh) if mesh is not None else {},
    }
    # Template SHAPES for the reshard global-shape check, read straight
    # off the live params (master_template changes only DTYPES, never
    # shapes — going through it, even under eval_shape, would execute
    # its concrete np.zeros and allocate a full f32 host tree just to
    # read shapes). Computed lazily the first time a foreign-shape
    # candidate is considered.
    tmpl_shapes_memo: list[dict] = []

    def template_shapes() -> dict:
        if not tmpl_shapes_memo:
            tmpl_shapes_memo.append({
                jax.tree_util.keystr(p): [int(d) for d in
                                          getattr(leaf, "shape", ())]
                for p, leaf in
                jax.tree_util.tree_flatten_with_path(state.params)[0]
            })
        return tmpl_shapes_memo[0]

    def candidate_gate(s: int) -> tuple[bool, bool, dict | None]:
        """(restorable, reshaped, sharding manifest) for step s — the
        census validity plus the topology gate. Deterministic from the
        shared volume + flags, so every replica reaches the same verdict
        (the broadcast agreement below then only guards VISIBILITY)."""
        if not ckpt.validate_step(ckpt_dir, s):
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": "invalid_checkpoint"})
            return False, False, None
        sm = ckpt.read_sharding_manifest(ckpt_dir, f"step_{s}")
        if sm is None:
            # Pre-manifest / hand-written checkpoint: unverifiable, not
            # invalid — restorable under same-shape semantics only.
            if allow_reshape:
                _emit({"event": "resume_fallback", "step": s,
                       "reason": "missing_sharding_manifest: shape "
                                 "unverifiable, same-shape restore only"})
            return True, False, None
        saved = {
            "processCount": int(sm.get("processCount") or 0),
            "mesh": {k: int(v)
                     for k, v in (sm.get("mesh") or {}).items()},
        }
        if saved == cur_shape:
            return True, False, sm
        if not allow_reshape:
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": (
                       f"foreign_shape: saved on "
                       f"{saved['processCount']} process(es), mesh "
                       f"{saved['mesh']} (running "
                       f"{cur_shape['processCount']}, "
                       f"{cur_shape['mesh']}); pass --allow-reshape to "
                       f"reshard")})
            return False, False, sm
        # Reshard path: the GLOBAL shapes must match the template leaf
        # for leaf — a mismatch is a model-config change, and walking
        # past it beats restoring garbage.
        saved_shapes = {k: v.get("shape")
                        for k, v in (sm.get("leaves") or {}).items()}
        if saved_shapes != template_shapes():
            _emit({"event": "resume_fallback", "skipped_step": s,
                   "reason": "reshard_shape_mismatch: per-leaf global "
                             "shapes differ from this model config"})
            return False, False, sm
        return True, True, sm

    def next_restorable(start_idx: int) -> tuple[int, int | None, bool,
                                                 dict | None]:
        """(index, step, reshaped, sharding manifest) of the first
        restorable candidate at/after start_idx. Lazy on purpose: only
        checkpoints actually walked PAST are validated (and get a
        resume_fallback event) — a stale torn step older than the chosen
        candidate costs nothing and emits nothing, and a long-retention
        dir is never fully os.walk'd inside the restart path."""
        i = start_idx
        while i < len(ordered):
            ok, reshaped, sm = candidate_gate(ordered[i])
            if ok:
                return i, ordered[i], reshaped, sm
            i += 1
        return len(ordered), None, False, None

    idx, last, reshaped, sharding_m = next_restorable(0)
    if jax.process_count() > 1:
        # Every replica independently reads the checkpoint dir; if visibility
        # differs (non-shared volume, storage lag) the replicas would resume
        # divergent states AND compile different scan unrolls — mismatched
        # collectives hang the job. The agreement collective must run on
        # EVERY process (sentinel -1 = sees nothing) BEFORE any early
        # return, else the check itself deadlocks. (Validation is a
        # deterministic read of the shared volume, so agreeing on the
        # chosen candidate subsumes agreeing on latest_step.)
        from jax.experimental import multihost_utils
        import numpy as np

        observed = -1 if last is None else last
        agreed = int(multihost_utils.broadcast_one_to_all(np.int32(observed)))
        if agreed != observed:
            raise RuntimeError(
                f"checkpoint visibility differs across replicas (this process "
                f"sees step {observed}, process 0 sees {agreed}) — mount a "
                f"shared --checkpoint-dir volume"
            )
    if last is None:  # step_0 is a valid (externally seeded) checkpoint
        if all_steps:
            print(
                f"warning: no restorable checkpoint under {ckpt_dir} "
                f"(all {len(all_steps)} step dirs failed validation) — "
                f"cold-starting from step 0",
                file=sys.stderr,
            )
            _emit({"event": "resume_fallback", "to_step": 0,
                   "reason": "no_valid_checkpoint",
                   "steps_seen": len(all_steps)})
        return state, 0
    p_template = jax.device_get(
        optim_lib.master_template(tx, jax.device_get(state.params))
    )
    params = None
    while last is not None:
        try:
            params = ckpt.restore(ckpt_dir, last, template=p_template)
            break
        except Exception as e:  # noqa: BLE001 — a torn tree raises anything
            if jax.process_count() > 1:
                # The replicas agreed on `last` only; silently walking
                # further here could diverge — fail loud, retry the pod.
                raise
            _emit({"event": "resume_fallback", "skipped_step": last,
                   "reason": f"restore_error: {type(e).__name__}: {e}"})
            idx, last, reshaped, sharding_m = next_restorable(idx + 1)
    if params is None:
        print(
            f"warning: every checkpoint under {ckpt_dir} failed to "
            f"restore — cold-starting from step 0",
            file=sys.stderr,
        )
        _emit({"event": "resume_fallback", "to_step": 0,
               "reason": "no_valid_checkpoint", "steps_seen": len(all_steps)})
        return state, 0
    step_arr = jnp.asarray(last, jnp.int32)
    opt_state, model_state, partial = state.opt_state, state.model_state, True
    try:
        if not ckpt.validate_named(ckpt_dir, f"trainstate_{last}"):
            # Torn aux payload with an intact params dir: params-only
            # resume (fresh optimizer) beats walking further back.
            _emit({"event": "resume_fallback", "skipped_step": last,
                   "reason": "invalid_trainstate", "params_only": True})
            raise FileNotFoundError(f"trainstate_{last}")
        aux = ckpt.restore_named(
            ckpt_dir, f"trainstate_{last}", template=jax.device_get(_aux_tree(state))
        )
    except Exception:  # noqa: BLE001 — any unreadable aux degrades, below
        # params-only checkpoint (or a trainstate written under a different
        # optimizer layout — orbax raises ValueError on the leaf-list arity
        # mismatch — or torn past its manifest): fresh optimizer, step from
        # the dir name. Under master_weights the fresh f32 master must
        # mirror the restored params, not the session's random init.
        if isinstance(tx, optim_lib.MixedPrecisionTransformation) \
                and tx.config.master_weights:
            opt_state = tx.init(params)
    else:
        step_arr = jnp.asarray(aux["step"], jnp.int32)
        opt_state = jax.tree.unflatten(
            jax.tree.structure(state.opt_state), aux["opt_leaves"]
        )
        model_state = aux.get("model_state", state.model_state)
        partial = False
    if jax.process_count() > 1:
        # The replicas already agreed on the STEP; they must also agree on
        # full-vs-params-only, or one replica trains with restored Adam
        # moments while another re-initialized them — shapes match, the
        # collectives run, and the model silently diverges. Runs on every
        # process (same rule as the step agreement above).
        from jax.experimental import multihost_utils
        import numpy as np

        mine = 1 if partial else 0
        agreed_partial = int(
            multihost_utils.broadcast_one_to_all(np.int32(mine))
        )
        if agreed_partial != mine:
            raise RuntimeError(
                f"trainstate_{last} visibility differs across replicas "
                f"(this process resumes {'params-only' if mine else 'full'}"
                f", process 0 {'params-only' if agreed_partial else 'full'})"
                f" — shared --checkpoint-dir volume lagging; retrying"
            )
    state = TrainState(
        step=step_arr, params=optim_lib.compute_params(tx, params),
        opt_state=opt_state, model_state=model_state,
    )
    start = int(step_arr)
    def _dtypes_match(saved_leaves, tree) -> bool:
        """crc32 bytes are only comparable when every leaf restored at
        its SAVED dtype — a master-weights f32 upcast of a bf16 compute
        checkpoint is a correct restore whose bytes legitimately differ,
        and reporting that as a digest mismatch would read as
        corruption."""
        got = {jax.tree_util.keystr(p): str(getattr(leaf, "dtype", ""))
               for p, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]}
        want = {k: v.get("dtype") for k, v in (saved_leaves or {}).items()}
        return want == got

    event = {"event": "resumed", "from_step": start, "params_only": partial}
    saved_digest = (sharding_m.get("digest") or {}) if sharding_m else {}
    if saved_digest:
        # Bit-equality witness: crc32 of the restored host bytes vs what
        # the save recorded (only written when the job opted into
        # reshaping). Equal digests PROVE the round trip (incl. a
        # resharded one) returned exactly the saved state; trees whose
        # dtypes changed across the round trip are skipped, not reported
        # as mismatches.
        digest = {}
        if ("params" in saved_digest
                and _dtypes_match(sharding_m.get("leaves"), params)):
            digest["params"] = ckpt.tree_digest(params)
        if (not partial and "trainstate" in saved_digest
                and _dtypes_match(sharding_m.get("auxLeaves"), aux)):
            digest["trainstate"] = ckpt.tree_digest(aux)
        if digest:
            event["digest"] = digest
            event["saved_digest"] = {k: saved_digest[k] for k in digest}
    if reshaped:
        event["reshaped"] = {
            "from_processes": int(sharding_m.get("processCount") or 0),
            "from_mesh": sharding_m.get("mesh") or {},
            "to_processes": jax.process_count(),
            "to_mesh": cur_shape["mesh"],
        }
    _emit(event)
    return state, start


def _preempt_exit(args, guard, state, done, saver, last_save_s,
                  last_ckpt_step, st=None) -> int:
    """Graceful-preemption teardown at a step boundary: drain any
    in-flight async checkpoint write first (its seconds burn the grace
    budget through guard.elapsed()), ADOPT the drained save as the
    emergency checkpoint when it is newer-or-equal to this boundary, else
    run the synchronous fast path when the remaining budget still covers
    the estimated save cost. Emits the `preempted` event and hands back
    128+signum for the operator's EXIT_CODE policy to classify as
    retryable."""
    saved = False
    skipped = None
    drain_s = None
    adopted = False
    if saver and args.checkpoint_dir:
        writer = _ckpt_writer
        if writer is not None:
            # Drain, don't abandon: the in-flight write is mostly on disk
            # already, and an orphaned writer racing process teardown
            # would strand a tmp dir a clean drain turns into a usable
            # emergency checkpoint. Errors degrade to the fast path.
            drain_s = writer.drain(raise_error=False)
            # Post-drain the writer's means include the write that was in
            # flight at submit time — last_save_s (estimated at submit,
            # when the FIRST write's cost was still unknown and read as
            # 0) can underestimate a sync emergency save by the whole
            # write leg, exactly the overrun within_grace exists to veto.
            # mean_save_s = snapshot + write, the full inline cost.
            last_save_s = max(last_save_s, writer.mean_save_s())
            if (writer.error is None and writer.last_step is not None
                    and writer.last_step >= done):
                saved = True
                adopted = True
        if not saved:
            if writer is None and done == last_ckpt_step:
                saved = True  # this boundary's periodic sync save landed
            elif guard.within_grace(last_save_s, args.preempt_grace):
                _save_checkpoint(args.checkpoint_dir, done, state,
                                 keep=args.keep_checkpoints, st=st,
                                 sync=True)
                saved = True
            else:
                skipped = "grace_budget"
    event = {
        "event": "preempted",
        "step": done,
        "signal": guard.signal_name,
        "exit_code": guard.exit_code,
        "emergency_checkpoint": saved,
        "grace_s": args.preempt_grace,
        "elapsed_s": round(guard.elapsed(), 3),
    }
    if drain_s is not None:
        event["drain_s"] = round(drain_s, 3)
    if adopted:
        event["adopted_async_save"] = True
    if skipped:
        event["save_skipped"] = skipped
    _emit(event)
    _maybe_export_trace(args)
    # No distributed_goodbye: in a real eviction every replica got the
    # signal; synchronizing a teardown barrier against dying peers would
    # burn the grace window.
    return guard.exit_code


def _run_evaluator(args, model, params_template, make_batch, loss_fn,
                   guard) -> int:
    """Evaluator replica: follow the checkpoint stream until FINAL
    (the reference's Evaluator role, excluded from the ClusterSpec)."""
    import jax

    from tf_operator_tpu.models import checkpoint as ckpt

    if not args.checkpoint_dir:
        print("--eval requires --checkpoint-dir", file=sys.stderr)
        return 2

    @jax.jit
    def eval_loss(params, batch):
        loss, _ = loss_fn(params, {}, batch, jax.random.key(0))
        return loss

    seen: set[int] = set()
    evaluated = 0
    while True:
        step = ckpt.wait_for_new_step(
            args.checkpoint_dir, seen, timeout=args.eval_timeout,
            # The guard only LATCHES signals now, so without this check an
            # evaluator would sit out the whole eval timeout under SIGTERM
            # and die by the kubelet's SIGKILL instead of exiting cleanly.
            should_stop=lambda: guard.triggered,
        )
        if guard.triggered:
            _emit({"event": "preempted", "role": "evaluator",
                   "signal": guard.signal_name, "exit_code": guard.exit_code,
                   "checkpoints_evaluated": evaluated})
            return guard.exit_code
        if step is None:
            final = ckpt.final_step(args.checkpoint_dir)
            if final is not None and final in seen:
                break  # stream complete
            print(f"evaluator: no new checkpoint in {args.eval_timeout}s",
                  file=sys.stderr)
            # No distributed teardown: the evaluator is excluded from
            # the SPMD process world (cluster_spec only enrolls
            # chief/master/worker), so it is always single-process.
            return 1 if evaluated == 0 else 0
        seen.add(step)
        params = ckpt.restore(args.checkpoint_dir, step, template=params_template)
        # Fixed keys -> the same eval batches every round, generated lazily
        # (materializing all of them up front would hold steps×batch arrays).
        with telemetry.span("eval", checkpoint_step=step, n_batches=args.steps):
            losses = [
                float(eval_loss(params, make_batch(jax.random.key(10_000 + i))))
                for i in range(args.steps)
            ]
        evaluated += 1
        _emit({
            "event": "eval",
            "checkpoint_step": step,
            "eval_loss": round(sum(losses) / len(losses), 6),
            "n_batches": args.steps,
        })
    _emit({"event": "eval_done", "checkpoints_evaluated": evaluated})
    return 0


def _train_on_dataset(args, state, start_step, loss_fn, tx, mesh, rules,
                      saver, t_start, guard, xla_options=None) -> int:
    """Real-data loop: host batches from the sharded dataset, staged onto
    the device so the transfer of batch i+K rides under the compute of
    batch i. Each process reads its own shards (shard_from_env) and feeds
    its slice of the GLOBAL batch.

    Two ingest modes (--input-staging): "prefetch" is the PR-1 double-
    buffered device_put thread (kept as the continuity baseline the bench's
    unstaged point tracks); "staged" is the round-7 staging ring
    (data/staging.py) — wire-dtype control, chunked puts, and first-class
    transfer/overlap accounting. Both route through the same on-device
    preprocess hook, so the uint8 wire normalizes inside the jitted step."""
    import jax

    from tf_operator_tpu.data import (
        ShardedDataset,
        prefetch_to_device,
        shard_from_env,
        stage_to_device,
    )
    from tf_operator_tpu.data import staging as staging_lib
    from tf_operator_tpu.parallel import mesh as mesh_lib
    from tf_operator_tpu.parallel.train_step import make_train_step

    nprocs = jax.process_count()
    if args.batch % nprocs:
        raise SystemExit(f"--batch {args.batch} not divisible by {nprocs} processes")
    reader, readers = shard_from_env()
    ds = ShardedDataset(args.data_dir, reader, readers)
    # start_batch keeps a resumed run on the uninterrupted batch sequence
    # (one local batch per global step). The stats dicts measure how much
    # of the input path (host batch production + host->device transfer)
    # actually hides under compute — reported in the done event so the
    # bench can quantify the overlap instead of asserting it.
    host_it = ds.batches(args.batch // nprocs, seed=0, start_batch=start_step)
    batch_sh = mesh_lib.batch_sharding(mesh)
    prefetch_stats: dict = {}
    staging_stats: dict = {}
    staging_tune = None
    if args.input_staging == "staged":
        lanes, chunks = args.staging_lanes, args.staging_chunks
        if args.staging_tune:
            # Peek ONE host batch, probe {lanes x chunks} against the live
            # link with copies of it, then chain it back in front — the
            # training trajectory is byte-identical to an untuned run
            # (pinned by test), only the engine geometry changes.
            import itertools

            first = next(host_it)
            # depth = the run's real ring depth, so every probe runs the
            # geometry the job will (the ring caps lanes at depth — a
            # winner probed at a deeper ring would lock an unprobed
            # configuration)
            staging_tune = staging_lib.autotune_staging(
                first, sharding=batch_sh, wire_dtype=args.wire_dtype,
                codec=args.wire_codec, depth=args.staging_depth,
            )
            lanes, chunks = staging_tune["lanes"], staging_tune["chunks"]
            host_it = itertools.chain([first], host_it)
            _emit({"event": "staging_tuned", "lanes": lanes,
                   "chunks": chunks,
                   "mb_per_s": staging_tune["mb_per_s"],
                   "probe_s": staging_tune["probe_s"]})
        it = stage_to_device(
            host_it,
            depth=args.staging_depth,
            sharding=batch_sh,
            chunks=chunks,
            wire_dtype=args.wire_dtype,
            stats=staging_stats,
            lanes=lanes,
            codec=args.wire_codec,
        )
    else:
        it = prefetch_to_device(
            (staging_lib.to_wire(b, args.wire_dtype) for b in host_it),
            depth=2,
            sharding=batch_sh,
            stats=prefetch_stats,
        )
    _, compile_step = make_train_step(
        loss_fn, tx, mesh, rules=rules, remat=args.remat,
        # uint8 wire batches normalize on device, inside the step (batch
        # args are not donated — see make_train_step's donation note).
        preprocess_fn=staging_lib.make_preprocess_fn(),
    )

    batch = next(it)
    step = compile_step(state, batch, compiler_options=xla_options)
    state, metrics = step(state, batch, jax.random.key(start_step))
    # Host transfer (block_until_ready is a no-op through the axon tunnel):
    # startup_s must include the first step's device execution.
    first_loss = float(metrics["loss"])
    t_first = time.time()
    done = start_step + 1
    _emit(
        {
            "event": "first_step",
            "t": t_first,
            "startup_s": round(t_first - t_start, 3),
            "steps_in_first_call": 1,
            "loss": first_loss,
            "mesh": dict(mesh.shape),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": len(jax.devices()),
            "data_dir": args.data_dir,
            "local_samples": ds.num_samples,
        }
    )
    _hb(done, force=True)  # first optimizer step landed: liveness + step
    profiling = bool(args.profile_dir) and done < args.steps
    if profiling:
        _start_profile(args.profile_dir)
    # Same latency-hiding as the scanned loop: fetch step i's loss after
    # dispatching step i+1 so the transfer rides under compute (the
    # immediate fetch otherwise idles the chip one full tunnel round trip
    # per emit). Only the window-closing fetch blocks.
    # Phase accounting (telemetry/phases.py): every steady step decomposes
    # into data_wait / dispatch / device_blocked / checkpoint (+ "other"
    # residual) telescoping exactly to the step's wall-clock; the done
    # event carries the per-step distribution, not just the mean.
    t0 = time.time()
    pending = None
    last_save_s, last_ckpt_step = 0.0, -1
    acct = telemetry.make_step_accounting()
    while done < args.steps:
        _trace_window_check(args, done - start_step - 1)
        with acct.step(done + 1) as st:
            with st.phase("data_wait"):
                batch = next(it)
            with st.phase("dispatch"):
                state, metrics = step(state, batch, jax.random.key(done))
            done += 1
            if pending is not None:
                pstep, pmetrics = pending
                if pstep % args.log_every == 0:
                    with st.phase("device_blocked"):
                        ploss = float(pmetrics["loss"])
                    _emit({"event": "progress", "step": pstep,
                           "loss": ploss})
            pending = (done, metrics)
            if (saver and args.checkpoint_every and done < args.steps
                    and done % args.checkpoint_every == 0):
                # _save_checkpoint opens the phase itself: `checkpoint`
                # in sync mode, `ckpt_snapshot` (the only blocking leg)
                # under the async writer.
                last_save_s = _save_checkpoint(
                    args.checkpoint_dir, done, state,
                    keep=args.keep_checkpoints, st=st)
                last_ckpt_step = done
            # Step boundary: the progress heartbeat records the completed
            # step, chaos hang/kill-at-step fire here, and a latched
            # preemption signal (SIGTERM/SIGINT/SIGUSR1 — real or chaos-
            # injected) turns into emergency-checkpoint + exit 128+signum.
            _hb(done)
            _boundary_chaos(done, start_step)
            if guard.triggered:
                return _preempt_exit(args, guard, state, done, saver,
                                     last_save_s, last_ckpt_step, st)
    if pending is not None:
        # Real window closure: a host transfer (block_until_ready is a
        # no-op through the axon tunnel).
        pstep, pmetrics = pending
        closing_loss = float(pmetrics["loss"])
    dt = time.time() - t0
    if pending is not None:
        # The loop exits only at done == args.steps, so the final progress
        # event (pstep == args.steps) always emits.
        _emit({"event": "progress", "step": pstep, "loss": closing_loss})
    if profiling:
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": args.steps - start_step - 1})
    if saver:
        _save_checkpoint(args.checkpoint_dir, args.steps, state, final=True,
                         keep=args.keep_checkpoints)
    # The final step must land in the heartbeat whatever the throttle did
    # at intermediate boundaries (the watchdog/collector read it back).
    _hb(args.steps, force=True)
    steady = args.steps - start_step - 1
    sps = round(steady / dt, 4) if steady > 0 else None
    from tf_operator_tpu.data.prefetch import overlap_efficiency

    telem = acct.summary()
    done_event = {
        "event": "done",
        "t": time.time(),
        "steps": args.steps,
        "steady_steps_per_sec": sps,
        "examples_per_sec": round(steady * args.batch / dt, 4) if steady > 0 else None,  # 4 dp: 2-dp quantized batch-1 long-context rows by +-2.6%
        "final_loss": float(metrics["loss"]),
        "total_s": round(time.time() - t_start, 3),
        # Per-step wall-clock distribution + telescoping phase breakdown
        # (telemetry/phases.py): p99 stalls are invisible in the mean.
        "step_time_s": telem["step_time_s"] if telem else None,
        "phase_breakdown": telem["phase_breakdown"] if telem else None,
    }
    ckpt_block = _ckpt_done_stats()
    if ckpt_block:
        # Zero-stall checkpointing accounting: snapshot_s is what the
        # step loop paid, write_s what the writer thread hid (or didn't:
        # hidden_fraction, drains — see docs/perf.md's stall model).
        done_event["checkpoint"] = ckpt_block
    if args.input_staging == "staged":
        # First-class transfer + overlap accounting from the staging ring's
        # own timers (data/staging.py): the bench's staged point reads these
        # as transfer_mb_per_s / input_overlap_fraction.
        rate = staging_lib.transfer_mb_per_s(staging_stats)
        overlap = staging_lib.input_overlap_fraction(staging_stats)
        done_event["staging"] = {
            "depth": args.staging_depth,
            # chunks/lanes that RAN (the tuner may have overridden the
            # flags; chunks_effective/lanes_effective say what the engine
            # then degraded them to per-array / per-path)
            "chunks": chunks,
            # what the knob actually did: degraded per-array (size/shard
            # divisibility) and inactive on multi-process jobs — a tuned
            # --staging-chunks that reads back 1 here did nothing
            "chunks_effective": staging_stats.get("chunks_effective"),
            "lanes": lanes,
            "lanes_effective": staging_stats.get("lanes_effective"),
            "wire_dtype": args.wire_dtype,
            "codec": args.wire_codec,
            "batches": staging_stats.get("batches_consumed"),
            # staged >= consumed: the ring reads ahead up to `depth`
            # batches the step loop never drained (bytes_staged covers
            # staged, so the two are reported together)
            "batches_staged": staging_stats.get("batches_staged"),
            "bytes_staged_mb": round(
                staging_stats.get("bytes_staged", 0) / 1e6, 3),
            "transfer_s": round(staging_stats.get("transfer_s", 0.0), 3),
            # union wall-clock with >= 1 lane on the wire — the clock
            # behind transfer_mb_per_s (== transfer_s when single-lane)
            "transfer_busy_s": round(
                staging_stats.get("transfer_busy_s", 0.0), 3),
            "transfer_mb_per_s": round(rate, 2) if rate is not None else None,
            "input_overlap_fraction": (
                round(overlap, 4) if overlap is not None else None),
            # consumer wall-clock decomposition; wait + busy == wall by
            # construction (tests pin it), so nothing is unaccounted.
            "wall_s": round(staging_stats.get("wall_s", 0.0), 3),
            "consumer_wait_s": round(
                staging_stats.get("consumer_wait_s", 0.0), 3),
            "consumer_busy_s": round(
                staging_stats.get("consumer_busy_s", 0.0), 3),
        }
        if args.wire_codec != "none":
            # Codec cost/benefit ledger: what a compressed remote wire
            # would carry vs what the codec burned in lane CPU — the
            # decision input for a compressed tunnel protocol.
            enc = staging_stats.get("bytes_encoded", 0)
            raw = staging_stats.get("bytes_staged", 0)
            done_event["staging"].update({
                "bytes_encoded_mb": round(enc / 1e6, 3),
                "codec_ratio": round(raw / enc, 3) if enc else None,
                "encode_s": round(staging_stats.get("encode_s", 0.0), 3),
                "decode_s": round(staging_stats.get("decode_s", 0.0), 3),
            })
        if staging_tune is not None:
            # The startup probe table (autotune_staging): why the tuner
            # locked this {lanes x chunks} — audit trail for the bench.
            done_event["staging"]["tune"] = staging_tune
    else:
        # Measured input-path overlap (VERDICT r5 weak-#4): what share
        # of host production + host->device transfer rode under
        # compute, from the prefetcher's own timers.
        overlap = overlap_efficiency(prefetch_stats)
        done_event["prefetch"] = {
            "batches": prefetch_stats.get("batches_consumed"),
            "input_s": round(prefetch_stats.get("input_s", 0.0), 3),
            "consumer_wait_s": round(
                prefetch_stats.get("consumer_wait_s", 0.0), 3),
            "overlap_efficiency": (
                round(overlap, 4) if overlap is not None else None),
        }
    _emit(done_event)
    _maybe_export_trace(args)
    # Synchronized multi-process exit (no-op single-process): see
    # parallel.distributed.distributed_goodbye.
    from tf_operator_tpu.parallel.distributed import distributed_goodbye

    distributed_goodbye()
    return 0


def _train_multislice(args, state, start_step, loss_fn, tx, mesh, rules,
                      make_batch, rebuild_state, saver, t_start, guard,
                      world) -> int:
    """Multi-slice training loop (TPUJOB_NUM_SLICES > 1): this process's
    jax world spans ONE slice; cross-slice data parallelism rides the
    emulated DCN exchange (parallel/multislice.py).

    Per global step: M microbatch backwards are dispatched up front
    (async); as each lands, its within-slice-reduced gradients stream to
    the exchange — bucket transfers of microbatch m ride under the
    backward of m+1 — then the step loop blocks only for the exchange
    tail (`dcn_sync` phase) and applies the DCN-reduced mean with donated
    state. The mean over all slice x microbatch row blocks of the SAME
    global batch equals the full-batch mean, so the trajectory matches a
    single-slice reference run rtol-tight.

    Per-slice recovery: when a peer slice's gang is rolled, collect()
    holds at the barrier (heartbeat kept fresh via the tick — the
    operator must NOT roll this slice) until the restarted peer announces
    its resume from the shared checkpoint; SliceRewind then re-restores
    the same checkpoint IN PROCESS and the loop replays forward — no pod
    restart on the surviving slices, `gang_restarts` counts the incident
    once."""
    import jax
    import numpy as np

    from tf_operator_tpu.parallel import multislice as ms_lib
    from tf_operator_tpu.parallel.train_step import (
        make_multislice_step_fns,
        shard_state,
    )

    S, M = world.num_slices, args.dcn_microbatches
    if args.batch % (S * M):
        raise SystemExit(
            f"--batch {args.batch} not divisible by slices x microbatches "
            f"({S} x {M})"
        )
    rows = args.batch // (S * M)
    # The within-slice chips share each bucket's DCN transfer after the
    # ICI reduce-scatter (hierarchical-collective arithmetic): the
    # bandwidth dial charges the 1/ici_degree fraction only.
    world.ici_degree = jax.device_count()
    compile_fns = make_multislice_step_fns(
        loss_fn, tx, mesh, make_batch, rules=rules, rows=rows,
        remat=args.remat,
    )
    gen_batch, backward, apply_fn = compile_fns(state)
    ex = ms_lib.DcnExchange(
        world, resume_step=start_step, microbatches=M,
        buckets=args.dcn_buckets, peer_timeout_s=args.dcn_peer_timeout,
    )
    sid = world.slice_id
    g_treedef = None
    done = start_step
    first_done = False
    t0 = None
    steady_start = start_step
    acct = telemetry.make_step_accounting()
    last_save_s, last_ckpt_step = 0.0, -1
    final_loss = None

    def tick():
        # Holding at the barrier is LIVE: refresh the heartbeat's t (step
        # unchanged) so the operator's watchdog never rolls a survivor.
        _hb(done)

    try:
        while done < args.steps:
            try:
                with acct.step(done + 1) as st:
                    step = done + 1
                    ex.begin_step(step)
                    futs = []
                    with st.phase("dispatch"):
                        # The step's full batch is generated ONCE; each
                        # microbatch backward slices its row block out.
                        batch = gen_batch(done)
                        for m in range(M):
                            futs.append(backward(
                                state, batch, done, (sid * M + m) * rows))
                    for m in range(M):
                        # device_get blocks until microbatch m's backward
                        # lands; the exchange engine streams m-1's buckets
                        # (and peers' arrivals) meanwhile — that
                        # concurrency is the overlap being measured.
                        with st.phase("device_blocked"):
                            loss_m, grads_m = jax.device_get(futs[m])
                        if g_treedef is None:
                            g_treedef = jax.tree.structure(grads_m)
                        leaves = jax.tree.leaves(grads_m)
                        ex.submit(step, m, [
                            np.asarray(loss_m, np.float32).reshape(1)
                        ] + leaves)
                    with st.phase("dcn_sync"):
                        reduced = ex.collect(
                            step, tick=tick,
                            should_stop=lambda: guard.triggered)
                    gloss = float(reduced[0][0])
                    grads = jax.tree.unflatten(g_treedef, reduced[1:])
                    with st.phase("dispatch"):
                        state, _gnorm = apply_fn(state, grads)
                    done = step
                    final_loss = gloss
                    ex.step_done(done)
                    if not first_done:
                        first_done = True
                        t_first = time.time()
                        _emit({
                            "event": "first_step",
                            "t": t_first,
                            "startup_s": round(t_first - t_start, 3),
                            "steps_in_first_call": 1,
                            "loss": gloss,
                            "mesh": dict(mesh.shape),
                            "backend": jax.default_backend(),
                            "device_kind": jax.devices()[0].device_kind,
                            "n_devices": len(jax.devices()),
                            "slices": S,
                            "slice_id": sid,
                        })
                        _hb(done, force=True)
                        t0 = time.time()
                        steady_start = done
                    elif done % args.log_every == 0 or done == args.steps:
                        # The DCN-reduced loss is already on the host: a
                        # progress emit costs no device fetch here.
                        _emit({"event": "progress", "step": done,
                               "loss": gloss})
                    if (saver and args.checkpoint_every
                            and done < args.steps
                            and done % args.checkpoint_every == 0):
                        last_save_s = _save_checkpoint(
                            args.checkpoint_dir, done, state,
                            keep=args.keep_checkpoints, st=st)
                        last_ckpt_step = done
                    _hb(done)
                    _boundary_chaos(done, start_step)
                    if guard.triggered:
                        return _preempt_exit(args, guard, state, done,
                                             saver, last_save_s,
                                             last_ckpt_step, st)
            except ms_lib.DcnInterrupted:
                # Preemption latched while holding at the barrier (a
                # whole-job eviction SIGTERMs every slice — all of them
                # sit in collect): abandon the hold and run the graceful
                # path at the last COMPLETED step. The in-flight step's
                # partial exchange is discarded; the resumed job replays
                # it.
                return _preempt_exit(args, guard, state, done, saver,
                                     last_save_s, last_ckpt_step)
            except ms_lib.SliceRewind as rw:
                # A peer's gang was rolled and resumed behind us: meet it
                # at the shared checkpoint without restarting this pod.
                _emit({"event": "dcn_rewind", "from_step": done,
                       "peer_slice": rw.peer, "peer_resume": rw.to_step})
                state = rebuild_state()
                state, done = _try_resume(args.checkpoint_dir, state, tx,
                                          mesh=mesh)
                state = shard_state(state, mesh, rules)
                ex.rewind_to(done)
                _hb(done, force=True)
                continue
    except ms_lib.DcnPeerTimeout as e:
        # A peer never came back (double failure / operator wedged): exit
        # retryable so THIS slice's gang rolls too and the job recovers
        # whole from the shared checkpoint.
        print(f"dcn exchange: {e}; exiting retryable", file=sys.stderr)
        _emit({"event": "dcn_peer_timeout", "step": done, "detail": str(e)})
        _maybe_export_trace(args)
        from tf_operator_tpu.utils.exit_codes import EXIT_USER_RETRYABLE

        return EXIT_USER_RETRYABLE
    finally:
        dcn_stats = ex.stats()
        ex.close()

    if saver:
        _save_checkpoint(args.checkpoint_dir, args.steps, state, final=True,
                         keep=args.keep_checkpoints)
    _hb(args.steps, force=True)
    dt = (time.time() - t0) if t0 is not None else 0.0
    steady = args.steps - steady_start
    sps = round(steady / dt, 4) if steady > 0 and dt > 0 else None
    telem = acct.summary()
    done_event = {
        "event": "done",
        "t": time.time(),
        "steps": args.steps,
        "steady_steps_per_sec": sps,
        "examples_per_sec": (round(steady * args.batch / dt, 4)
                             if steady > 0 and dt > 0 else None),
        "final_loss": final_loss,
        "total_s": round(time.time() - t_start, 3),
        "step_time_s": telem["step_time_s"] if telem else None,
        "phase_breakdown": telem["phase_breakdown"] if telem else None,
        # Hierarchical-reduction accounting: dcn_busy_s is the exchange's
        # total (wire + IO + reduce), dcn_sync_s what the step loop
        # visibly waited (the dcn_sync phase), hidden_fraction their
        # complement — the overlap win, measured (docs/perf.md).
        "dcn": dcn_stats,
    }
    ckpt_block = _ckpt_done_stats()
    if ckpt_block:
        done_event["checkpoint"] = ckpt_block
    _emit(done_event)
    _maybe_export_trace(args)
    from tf_operator_tpu.parallel.distributed import distributed_goodbye

    distributed_goodbye()
    return 0


def _logits_bytes(args, mesh, vocab_size: int) -> float:
    """Per-device f32 logits bytes for the chunked-CE cutover.

    Divides the global [B, T, V] tensor by dp x fsdp only (batch dim,
    sharded by construction: the trainer puts the batch dim of every input
    on dp/fsdp). tp AND sp are deliberately EXCLUDED. tp shards the vocab
    dim, and the loss then gathers along that sharded dim
    (take_along_axis), which GSPMD may resolve by all-gathering the
    full-vocab logits per device. sp's seq sharding of T reaches the
    logits only if GSPMD propagates the attention shard_map's seq
    sharding through the blocks and lm_head — the trainer never shards
    the batch's seq dim itself, so on a mesh where that propagation
    fails the per-device logits are 1/sp bigger than the estimate and
    the one-shot head OOMs (round-4 advice). Conservative over-estimate
    -> worst case is the slightly slower chunked head."""
    from tf_operator_tpu.parallel import mesh as mesh_lib

    shards = max(1, mesh_lib.axis_size(mesh, "dp")
                 * mesh_lib.axis_size(mesh, "fsdp"))
    return 4.0 * args.batch * args.seq * vocab_size / shards


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--model",
        default="mnist-mlp",
        choices=["mnist-mlp", "mnist-conv", "resnet18", "resnet50",
                 "transformer-lm", "bert-base", "bert-tiny", "moe-lm"],
    )
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4,
                    help="transformer-lm/moe-lm depth")
    ap.add_argument("--hidden", type=int, default=512,
                    help="transformer-lm/moe-lm width")
    ap.add_argument("--heads", type=int, default=8,
                    help="transformer-lm/moe-lm attention heads")
    ap.add_argument("--moe-dispatch", default="dense",
                    choices=["dense", "sparse"],
                    help="moe-lm token dispatch: dense = GShard capacity "
                         "einsums (ep-shardable); sparse = dropless sorted "
                         "ragged matmul (ep=1 perf path)")
    ap.add_argument("--remat-save-flash", action="store_true",
                    help="with --remat (transformer-lm): save the flash "
                         "kernel's (o, lse) residuals so the backward "
                         "replays only linear ops, never the O(T^2) "
                         "kernel. Costs ~[B,T,H] bf16 per layer of HBM. "
                         "Fits (and is the bench config) at single-chip "
                         "64k since the round-5 chunked-CE fix; at 128k "
                         "use --remat-save-flash-layers instead")
    ap.add_argument("--remat-save-flash-layers", type=int, default=0,
                    help="with --remat (transformer-lm): save the flash "
                         "residuals for the FIRST K layers only (memory->"
                         "speed dial where saving all layers OOMs)")
    ap.add_argument("--remat", action="store_true",
                    help="activation checkpointing: rematerialize the loss, "
                         "and (transformer-lm) each block — saves only "
                         "block inputs for the backward at ~33%% extra "
                         "backward FLOPs; required for seq >= 64k on one "
                         "v5e chip")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--optimizer", default="adamw", choices=["adam", "adamw"])
    ap.add_argument("--moment-dtype", default="f32", choices=["f32", "bf16"],
                    help="Adam moment (mu/nu) STORAGE dtype; update math is "
                         "always f32. bf16 halves the optimizer-moment HBM "
                         "slab and its per-step read+write traffic "
                         "(docs/perf.md round-6 section)")
    ap.add_argument("--master-weights", action="store_true",
                    help="keep the authoritative f32 param copy in the "
                         "optimizer state and train on bf16 compute params "
                         "re-derived from it each step: fwd/bwd read 2-byte "
                         "weights while updates accumulate in f32. "
                         "Checkpoints round-trip both copies; legacy f32 "
                         "checkpoints still load (params-only, master "
                         "rebuilt from them)")
    ap.add_argument("--log-every", type=int, default=20)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="chief/worker-0 writes orbax checkpoints here; the "
                         "Evaluator replica follows them (--eval)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="save every N steps (default: once at the end)")
    ap.add_argument("--checkpoint-mode", default="async",
                    choices=["async", "sync"],
                    help="async (default): a save blocks the step loop "
                         "only for the device->host snapshot; the orbax "
                         "write + manifests + digests + retention ride a "
                         "dedicated writer thread (one in-flight save, "
                         "backpressure on the next; SIGTERM drains and "
                         "adopts the in-flight save when newer-or-equal). "
                         "sync: the historical fully-blocking save — the "
                         "bit-equality reference for the async pipeline "
                         "and the fallback if a storage backend mishandles "
                         "background writes")
    ap.add_argument("--allow-reshape", action="store_true",
                    help="accept a checkpoint saved at a DIFFERENT gang "
                         "shape (process count / mesh): restore reshards "
                         "every leaf (params + optimizer state) onto the "
                         "current mesh via the checkpoint's sharding "
                         "manifest. Without this flag a foreign-shape "
                         "checkpoint is skipped by the resume walk like a "
                         "corrupt one. The operator sets "
                         "TPUJOB_ALLOW_RESHAPE=1 on pods of jobs with "
                         "recovery.elastic.reshapeOnRecovery")
    ap.add_argument("--keep-checkpoints", type=int, default=0,
                    help="retention: after each save keep only the newest K "
                         "step checkpoints (params + trainstate + manifests) "
                         "and prune the rest; 0 (default) keeps everything. "
                         "Orphaned orbax tmp dirs are swept at startup "
                         "either way")
    ap.add_argument("--preempt-grace", type=float, default=30.0,
                    help="graceful-preemption budget in seconds, measured "
                         "from SIGTERM/SIGINT/SIGUSR1 receipt (the window "
                         "before the kubelet's SIGKILL): the trainer "
                         "finishes the in-flight step and writes an "
                         "emergency checkpoint only when the estimated "
                         "save still fits the budget; 0 never attempts "
                         "the emergency save. Exit is 128+signum either "
                         "way (143/130/138 — retryable under EXIT_CODE)")
    ap.add_argument("--chaos", default=None, metavar="SPEC",
                    help="fault-injection spec (same grammar as "
                         "TPUJOB_CHAOS, which it overrides): e.g. "
                         "'kill:step=12,signal=TERM' or "
                         "'torn:step=8;stall:every=3,delay=0.2' — see "
                         "docs/robustness.md")
    ap.add_argument("--eval", action="store_true",
                    help="evaluator mode: poll --checkpoint-dir, restore and "
                         "evaluate each new checkpoint until FINAL")
    ap.add_argument("--eval-timeout", type=float, default=600.0)
    ap.add_argument("--profile-dir", default=None,
                    help="write a jax.profiler (XProf/TensorBoard) trace of "
                         "the steady-state window to this directory")
    ap.add_argument("--trace", action="store_true",
                    help="record host-side spans (step phases, input "
                         "staging, checkpoint IO) in the in-process tracer "
                         "and export Chrome trace-event JSON at exit "
                         "(Perfetto / chrome://tracing). Composes with "
                         "--profile-dir: this is the host timeline, XProf "
                         "is the device one")
    ap.add_argument("--trace-dir", default=None,
                    help="directory for the trace file "
                         "(<replica rank>.trace.json; default ./traces)")
    ap.add_argument("--trace-steps", type=int, default=0,
                    help="stop recording after this many steady steps "
                         "(0 = the whole run, bounded by the tracer's "
                         "ring buffer)")
    ap.add_argument("--xla-option", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="per-executable XLA compiler option (repeatable), "
                         "forwarded via jit(...).lower().compile(). sparse "
                         "moe-lm on TPU defaults to "
                         "xla_tpu_scoped_vmem_limit_kib=49152: ragged_dot's "
                         "mosaic kernel at bench shapes needs ~22M (fwd) / "
                         "~34M (bwd) scoped VMEM vs the 16M default")
    ap.add_argument("--data-dir", default=None,
                    help="train on a sharded on-disk dataset (data/dataset.py "
                         "layout; keys must match the model's batch keys) "
                         "instead of synthetic data; --batch is the GLOBAL "
                         "batch, sharded across processes")
    ap.add_argument("--input-staging", default="prefetch",
                    choices=["prefetch", "staged"],
                    help="with --data-dir: host->device ingest mode. "
                         "'prefetch' = the double-buffered transfer thread "
                         "(continuity baseline); 'staged' = the staging "
                         "ring (data/staging.py): K device-batch slots, "
                         "optional chunked puts, and first-class "
                         "transfer-rate/overlap accounting in the done "
                         "event")
    ap.add_argument("--staging-depth", type=int, default=2,
                    help="staging ring size K: batches resident on device "
                         "ahead of the consumer (2 = double buffering)")
    ap.add_argument("--staging-chunks", type=int, default=1,
                    help="concurrent device_put transfers per staged array "
                         "(split along the batch dim, reassembled "
                         "on-device); >1 raises the effective rate on "
                         "links one serial put can't fill. Degrades "
                         "per-array to the largest feasible count (size "
                         "threshold, shard divisibility; inactive on "
                         "multi-process jobs) — the done event's "
                         "staging.chunks_effective records what ran")
    ap.add_argument("--staging-lanes", type=int, default=1,
                    help="transfer threads feeding the staging ring "
                         "CONCURRENTLY (each issues its own chunked "
                         "device_puts; ordered reassembly keeps exact "
                         "batch order). >1 raises the effective rate on "
                         "links where one put stream can't fill the pipe. "
                         "Capped at --staging-depth and inactive on "
                         "multi-process jobs — the done event's "
                         "staging.lanes_effective records what ran")
    ap.add_argument("--staging-tune", action="store_true",
                    help="micro-probe {lanes x chunks} combinations "
                         "against the live host->device link for a few "
                         "batches at startup and lock the best (overrides "
                         "--staging-lanes/--staging-chunks); the probe "
                         "table lands in the done event's staging.tune. "
                         "The probed batch is chained back into the "
                         "stream, so the training trajectory is identical "
                         "to an untuned run")
    ap.add_argument("--wire-codec", default="none",
                    choices=["none", "zlib"],
                    help="lossless wire compression for staged ingest: "
                         "encoded on the producer leg, decoded host-side "
                         "by the lane just before device_put (numerics "
                         "bit-identical). On a single-host runtime this "
                         "only MEASURES what a compressed remote wire "
                         "would save (staging.bytes_encoded_mb/"
                         "codec_ratio vs encode_s/decode_s)")
    ap.add_argument("--dcn-microbatches", type=int, default=2,
                    help="multi-slice jobs (TPUJOB_NUM_SLICES > 1): split "
                         "each step's backward into M microbatch "
                         "dispatches so the cross-slice (DCN) gradient "
                         "exchange of microbatch m streams while m+1 "
                         "computes — the compute/communication overlap "
                         "the done event's dcn.hidden_fraction measures. "
                         "1 = monolithic backward, exchange fully "
                         "visible. Ignored single-slice")
    ap.add_argument("--dcn-buckets", type=int, default=4,
                    help="gradient buckets per microbatch for the "
                         "cross-slice exchange (transfer granularity; "
                         "byte-balanced over the leaves). Ignored "
                         "single-slice")
    ap.add_argument("--dcn-peer-timeout", type=float, default=600.0,
                    help="multi-slice: how long a slice holds at the DCN "
                         "barrier waiting for its peers before exiting "
                         "retryable (a rolled peer announces its resume "
                         "well inside this; the timeout is the net under "
                         "pathological double failures)")
    ap.add_argument("--wire-dtype", default="auto",
                    choices=["auto", "uint8", "f32"],
                    help="with --data-dir: host->device wire format. auto = "
                         "ship arrays as stored (uint8 images stay uint8, "
                         "4x less wire than f32; normalization happens "
                         "on-device inside the step); uint8 = assert the "
                         "cheap wire (error if the dataset stores float "
                         "images); f32 = normalize on host and ship f32 "
                         "(the parity reference path)")
    args = ap.parse_args(argv)

    # Flag-only invariants fail HERE — before jax import, device dial, state
    # build, or checkpoint resume (minutes on a tunneled chip), and on every
    # path including --eval and resumed-complete early returns.
    if ((args.remat_save_flash or args.remat_save_flash_layers)
            and not args.remat):
        ap.error("--remat-save-flash[-layers] requires --remat (it selects "
                 "WHICH residuals per-layer remat keeps)")
    if args.remat_save_flash and args.remat_save_flash_layers:
        ap.error("--remat-save-flash (all layers) conflicts with "
                 "--remat-save-flash-layers K (a subset): pick one — the "
                 "all-layers flag would silently win and can OOM exactly "
                 "where the K dial was chosen to fit")
    if args.remat_save_flash_layers < 0:
        ap.error("--remat-save-flash-layers must be >= 0")
    for kv in args.xla_option:
        if "=" not in kv:
            ap.error(f"--xla-option must be KEY=VALUE, got {kv!r}")
    if args.staging_depth < 1:
        ap.error("--staging-depth must be >= 1")
    if args.staging_chunks < 1:
        ap.error("--staging-chunks must be >= 1")
    if args.staging_lanes < 1:
        ap.error("--staging-lanes must be >= 1")
    if args.dcn_microbatches < 1:
        ap.error("--dcn-microbatches must be >= 1")
    if args.dcn_buckets < 1:
        ap.error("--dcn-buckets must be >= 1")
    if args.dcn_peer_timeout <= 0:
        ap.error("--dcn-peer-timeout must be > 0")
    if not args.data_dir and (args.input_staging != "prefetch"
                              or args.wire_dtype != "auto"
                              or args.wire_codec != "none"
                              or args.staging_depth != 2
                              or args.staging_chunks != 1
                              or args.staging_lanes != 1
                              or args.staging_tune):
        ap.error("--input-staging/--wire-dtype/--wire-codec/"
                 "--staging-depth/--staging-chunks/--staging-lanes/"
                 "--staging-tune shape the --data-dir ingest path; "
                 "without --data-dir batches are synthesized on device "
                 "and there is no wire to shape")
    if (args.input_staging == "prefetch"
            and (args.staging_depth != 2 or args.staging_chunks != 1
                 or args.staging_lanes != 1 or args.staging_tune
                 or args.wire_codec != "none")):
        ap.error("--staging-depth/--staging-chunks/--staging-lanes/"
                 "--staging-tune/--wire-codec configure the staging "
                 "RING; with --input-staging prefetch they would be "
                 "silently ignored — pass --input-staging staged")
    if (args.trace_dir is not None or args.trace_steps) and not args.trace:
        ap.error("--trace-dir/--trace-steps shape the span trace; pass "
                 "--trace to enable it (they would otherwise be silently "
                 "ignored)")
    if args.trace_steps < 0:
        ap.error("--trace-steps must be >= 0")
    if args.preempt_grace < 0:
        ap.error("--preempt-grace must be >= 0")
    if args.keep_checkpoints < 0:
        ap.error("--keep-checkpoints must be >= 0")
    if args.keep_checkpoints and not args.checkpoint_dir:
        ap.error("--keep-checkpoints prunes --checkpoint-dir; without one "
                 "there is nothing to retain")
    if args.allow_reshape and not args.checkpoint_dir:
        ap.error("--allow-reshape shapes the --checkpoint-dir resume walk; "
                 "without one there is nothing to restore")
    from tf_operator_tpu import chaos as chaos_lib

    global _chaos
    chaos_env_prev = os.environ.get(chaos_lib.ENV_CHAOS)
    try:
        if args.chaos is not None:
            # Validate BEFORE mutating the env — a typo'd spec must fail
            # here without leaking into os.environ. The env write is the
            # one cross-layer channel (the staging ring and the fake
            # apiserver read it); main's finally restores it.
            chaos_lib.parse_chaos(args.chaos)
            os.environ[chaos_lib.ENV_CHAOS] = args.chaos
        _chaos = chaos_lib.TrainerChaos.from_env()
    except ValueError as e:
        ap.error(str(e))
    if args.trace:
        # Fresh window: clear() also restarts the ts epoch, so in-process
        # re-runs (tests, notebooks) don't leak a prior run's spans into
        # this run's export.
        telemetry.configure(enabled=True).clear()

    # Graceful preemption: handlers latch SIGTERM/SIGINT/SIGUSR1; the train
    # loops poll at step boundaries. Installed before the (slow) jax import
    # so a signal during startup is latched rather than fatal, and after
    # flag validation so ap.error paths never touch process-wide signal
    # disposition (in-process CLI tests included).
    from tf_operator_tpu.utils.preemption import HeartbeatWriter, PreemptionGuard

    guard = PreemptionGuard()
    guard.install()
    # Liveness from the very first moment: an immediate forced heartbeat
    # (before the slow jax import) tells the hang watchdog this generation
    # is alive even while startup/compile produces no step boundaries.
    global _heartbeat
    _heartbeat = HeartbeatWriter.from_env()
    _hb(0, force=True)

    try:
        return _run_trainer(args, guard)
    finally:
        # In-process-caller hygiene: hand back signal disposition and the
        # chaos env exactly as we found them, and drop the chaos state, so
        # a later chaos-free run in the same process stays chaos-free and
        # the host's Ctrl-C semantics survive this function.
        guard.uninstall()
        global _mesh, _digest_saves, _ckpt_writer
        if _ckpt_writer is not None:
            # Never leak the writer thread into an in-process caller
            # (tests, notebooks); close() also waits out an in-flight
            # write so an exception-path exit doesn't strand a tmp dir.
            # MUST run before _heartbeat/_chaos are nulled below: the
            # draining write leg still force-writes the durable-progress
            # heartbeat and consults _chaos for torn-checkpoint
            # directives.
            _ckpt_writer.close()
            _ckpt_writer = None
        _chaos = None
        chaos_lib.reset_ckpt_stall_state()
        _heartbeat = None
        _mesh = None
        _digest_saves = False
        with _sync_ckpt_lock:
            _sync_ckpt_stats.update(saves=0, snapshot_s=0.0, write_s=0.0)
        if args.chaos is not None:
            if chaos_env_prev is None:
                os.environ.pop(chaos_lib.ENV_CHAOS, None)
            else:
                os.environ[chaos_lib.ENV_CHAOS] = chaos_env_prev



def _run_trainer(args, guard) -> int:
    """Everything after flag validation and signal-guard install: device
    dial, model/optimizer build, resume, and the training loops. Split
    from main() so its MANY return paths share main's one finally (guard
    uninstall + chaos-env restore)."""

    t_start = time.time()
    _emit({"event": "start", "t": t_start, "model": args.model})

    from tf_operator_tpu.parallel.distributed import initialize_from_env

    initialize_from_env()
    # jax.distributed.initialize installs XLA's TSL PreemptionNotifier
    # SIGTERM handler over the guard's — without re-asserting, a
    # multi-process gang steps straight through a graceful eviction and
    # gets SIGKILLed checkpointless by the drain discipline.
    guard.reassert()

    import jax

    # Dial the accelerator while the rest of the stack imports: attaching a
    # (possibly tunneled) TPU backend is network-bound and independent of
    # the CPU-bound flax/optax import work, so the two overlap. The main
    # thread re-joins at mesh_from_env()'s jax.devices() call; an attach
    # error surfaces there, not in this daemon thread.
    import threading

    threading.Thread(
        target=lambda: jax.devices(), daemon=True, name="backend-dial"
    ).start()

    import jax.numpy as jnp

    from tf_operator_tpu.parallel import mesh as mesh_lib
    from tf_operator_tpu.parallel import sharding_rules
    from tf_operator_tpu.parallel.ring_attention import make_attention_fn
    from tf_operator_tpu.parallel.train_step import (
        create_train_state,
        make_scanned_train_step,
        shard_state,
        state_shardings,
    )
    from tf_operator_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    mesh = mesh_lib.mesh_from_env()
    global _mesh, _digest_saves
    _mesh = mesh  # checkpoint sharding manifests record the save-time mesh
    allow_reshape = (args.allow_reshape
                     or os.environ.get("TPUJOB_ALLOW_RESHAPE") == "1")
    # Digests ride the async write leg off the critical path, so they are
    # default-on whenever that leg exists; sync-mode jobs pay the two
    # full-tree passes inline only when elastic recovery needs the
    # witness (the original PR 9 opt-in rationale). Finalized below once
    # the writer is (or isn't) created — a requested-async job that falls
    # back to sync must not pay inline digests either.
    _digest_saves = allow_reshape
    # Segment timestamps (bench.py turns these into the startup breakdown
    # the north-star latency metric is judged on).
    _emit({"event": "jax_ready", "t": time.time(),
           "backend": jax.default_backend()})
    _hb(0, force=True)  # startup liveness milestone (pre state-build)
    rules = None
    # Each branch defines init_params(rng) -> (params, model_state) as a
    # TRACEABLE closure: the whole setup (init + optimizer) compiles into
    # one program with sharded outputs (see build_state below), instead of
    # dispatching dozens of tiny init ops — each a round-trip on a
    # tunneled chip — before training starts.

    if args.model in ("mnist-mlp", "mnist-conv"):
        from tf_operator_tpu.models import mnist as M

        model = M.MLP() if args.model == "mnist-mlp" else M.ConvNet()

        def init_params(rng):
            x = jnp.zeros((1, 28, 28), jnp.float32)
            return model.init(rng, x)["params"], {}

        def make_batch(rng):
            kx, ky = jax.random.split(rng)
            return {
                "x": jax.random.normal(kx, (args.batch, 28, 28)),
                "y": jax.random.randint(ky, (args.batch,), 0, 10),
            }

        def loss_fn(params, model_state, batch, rng):
            logits = model.apply({"params": params}, batch["x"])
            return M.cross_entropy_loss(logits, batch["y"]), model_state

    elif args.model in ("resnet18", "resnet50"):
        from tf_operator_tpu.models import mnist as M  # loss helpers
        from tf_operator_tpu.models.resnet import ResNet18, ResNet50, init_resnet

        classes = 1000
        model = (ResNet50 if args.model == "resnet50" else ResNet18)(
            num_classes=classes
        )

        def init_params(rng):
            params, batch_stats = init_resnet(
                model, rng, image_size=args.image_size, batch=2
            )
            return params, {"batch_stats": batch_stats}

        def make_batch(rng):
            kx, ky = jax.random.split(rng)
            return {
                "x": jax.random.normal(
                    kx, (args.batch, args.image_size, args.image_size, 3)
                ),
                "y": jax.random.randint(ky, (args.batch,), 0, classes),
            }

        def loss_fn(params, model_state, batch, rng):
            from tf_operator_tpu.data import staging as staging_lib

            x = batch["x"]
            if x.dtype == jnp.uint8:
                # Real pipelines ship uint8 pixels (4x less host->device
                # transfer than f32); normalize on device where it fuses
                # into the first conv's input read. The --data-dir path
                # normalizes in the step's preprocess hook with the SAME
                # helper, so this branch only fires for direct callers
                # handing the loss raw uint8 batches.
                x = staging_lib.normalize_uint8(x)
            logits, mut = model.apply(
                {"params": params, **model_state}, x, train=True,
                mutable=["batch_stats"],
            )
            return M.cross_entropy_loss(logits, batch["y"]), dict(mut)

    elif args.model in ("bert-base", "bert-tiny"):
        from tf_operator_tpu.models import transformer as tfm

        base = tfm.BERT_BASE if args.model == "bert-base" else tfm.TINY
        cfg = tfm.TransformerConfig(
            vocab_size=base.vocab_size, num_layers=base.num_layers,
            hidden=base.hidden, num_heads=base.num_heads,
            max_len=max(args.seq, 8), causal=False,
        )
        attn = make_attention_fn(mesh, causal=False)
        model = tfm.BertMLM(cfg, attn_fn=attn)

        def init_params(rng):
            return tfm.BertMLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.TRANSFORMER_TP_RULES

        def make_batch(rng):
            return tfm.make_mlm_batch(rng, args.batch, args.seq, cfg.vocab_size)

        def loss_fn(params, model_state, batch, rng):
            logits = model.apply({"params": params}, batch["tokens"])
            return (
                tfm.mlm_loss(logits, batch["targets"], batch["mask"]),
                model_state,
            )

    elif args.model == "moe-lm":
        from tf_operator_tpu.models import moe as moe_lib

        cfg = moe_lib.MoEConfig(
            vocab_size=32000, num_layers=args.layers, hidden=args.hidden,
            num_heads=args.heads, max_len=args.seq, num_experts=8, top_k=2,
            moe_every=2, dispatch=args.moe_dispatch,
        )
        attn = make_attention_fn(mesh, causal=True)
        model = moe_lib.MoETransformerLM(cfg, attn_fn=attn)

        def init_params(rng):
            return moe_lib.MoETransformerLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.MOE_RULES

        def make_batch(rng):
            return {
                "tokens": jax.random.randint(
                    rng, (args.batch, args.seq), 0, cfg.vocab_size
                )
            }

        # Same per-device logits-bytes cutover as transformer-lm: chunking
        # exists for memory, not speed — measured on-chip at the bench
        # shape (seq 2048) the scanned head LOSES ~2% (chunk 1024) to ~17%
        # (chunk 512) vs the full-logits path, which XLA epilogue-fuses.
        moe_chunked = _logits_bytes(args, mesh, cfg.vocab_size) >= 6e9

        def loss_fn(params, model_state, batch, rng):
            return (
                moe_lib.moe_lm_loss(model, params, batch["tokens"],
                                    chunked=moe_chunked),
                model_state,
            )

    else:  # transformer-lm
        from tf_operator_tpu.models import transformer as tfm

        cfg = tfm.TransformerConfig(
            vocab_size=32000, num_layers=args.layers, hidden=args.hidden,
            num_heads=args.heads, max_len=args.seq, causal=True,
            # --remat also remats per layer: at seq 64k the saved per-layer
            # intermediates alone exceed the chip (models/transformer.py
            # remat_layers note) — this is what makes 64k trainable.
            remat_layers=args.remat,
            # Selective policy: keep the flash (o, lse) residuals so the
            # backward never replays the O(T^2) kernel. Fits single-chip
            # 64k since the chunked-CE fix freed the stacked-logits
            # residuals (0.59 MFU, the bench config); sp-sharded
            # multi-chip jobs benefit even more (T/n-sized residuals).
            remat_save_flash=args.remat_save_flash,
            # Layer-subset middle ground: first K layers keep their flash
            # residuals (~100-200 MB each), dialing memory->speed where
            # saving all layers still OOMs (128k: cliff at K=10).
            remat_save_flash_layers=args.remat_save_flash_layers,
        )
        attn = make_attention_fn(mesh, causal=True)
        model = tfm.TransformerLM(cfg, attn_fn=attn)

        def init_params(rng):
            return tfm.TransformerLM(cfg).init(
                rng, jnp.zeros((1, args.seq), jnp.int32)
            )["params"], {}

        rules = sharding_rules.TRANSFORMER_TP_RULES

        def make_batch(rng):
            return {
                "tokens": jax.random.randint(
                    rng, (args.batch, args.seq), 0, cfg.vocab_size
                )
            }

        # When the full [B, T, vocab] f32 logits tensor gets big it (not
        # the activations) is the HBM peak: compute the head + softmax per
        # sequence chunk instead (numerics identical; see lm_loss_chunked).
        # Cutover on PER-DEVICE logits BYTES — batch scales the tensor
        # exactly like seq, but the batch dim is dp/fsdp-sharded, so the
        # global batch is divided by those axes first. Below the threshold
        # the one-shot head is measurably faster than the scan
        # (docs/perf.md): ~6 GB keeps every 4.2 GB case (8k b4, 16k b2,
        # 32k b1 single-chip) on the fast path on a 15.75 GB chip.
        chunked_loss = _logits_bytes(args, mesh, cfg.vocab_size) >= 6e9

        def loss_fn(params, model_state, batch, rng):
            if chunked_loss:
                h = model.apply(
                    {"params": params}, batch["tokens"], method="hidden"
                )
                loss = tfm.lm_loss_chunked(
                    h, params["lm_head"]["kernel"], batch["tokens"]
                )
                return loss, model_state
            logits = model.apply({"params": params}, batch["tokens"])
            return tfm.lm_loss(logits, batch["tokens"]), model_state

    if args.eval:
        import numpy as np

        # The evaluator only needs a host-side restore template (shapes +
        # dtypes) — never pay a device init for it.
        abstract_p, _ = jax.eval_shape(init_params, jax.random.key(0))
        template = jax.tree.map(
            lambda s: np.zeros(s.shape, s.dtype), abstract_p
        )
        rc = _run_evaluator(args, model, template, make_batch, loss_fn,
                            guard)
        # The evaluator records eval + checkpoint/restore spans; export
        # them on every exit path (timeout included — rc != 0 traces are
        # the interesting ones).
        _maybe_export_trace(args)
        return rc

    # Single-writer semantics differ by runtime shape. Independent
    # processes (PS-strategy: each worker is its own jax runtime): only the
    # chief/worker-0 touches the shared dir. ONE multi-process runtime
    # (jax.distributed): process 0 alone — checkpoint IO is PROCESS-LOCAL
    # since round 15 (the trees are host snapshots of fully-replicated
    # leaves, and checkpoint._checkpointer scopes every orbax barrier to
    # the calling process), so the historical every-process-enters-save
    # rule (which existed only to feed orbax's gang-wide barriers) is
    # gone — and with it the failure mode where one member's death
    # wedged every peer's save mid-barrier. EXCEPTION: a multi-process
    # world without a jax.distributed client (raw multi-host pod, no
    # operator env) has no scoped barriers — there the legacy rule
    # stands: every process enters the (gang-wide, collective) save, and
    # async stands down below.
    from tf_operator_tpu.models import checkpoint as _ckpt_mod
    from tf_operator_tpu.parallel import multislice as ms_lib

    # Multi-slice (TPUJOB_NUM_SLICES > 1): this jax world spans ONE slice;
    # the cross-slice layer is the DCN exchange. Detected here — before
    # the writer-role decision, which it changes.
    ms_world = ms_lib.SliceWorld.from_env()

    plocal_io = _ckpt_mod.process_local_io()
    if ms_world is not None:
        # ONE checkpoint writer across ALL slices: the global worker-0
        # (slice 0's leader). Every slice's world has its own process 0,
        # so the per-world rule below would elect one writer PER SLICE —
        # concurrent orbax writes into the shared dir.
        saver = (args.checkpoint_dir and _is_checkpoint_writer()
                 and jax.process_index() == 0)
    elif jax.process_count() > 1:
        saver = args.checkpoint_dir and (
            jax.process_index() == 0 if plocal_io else True
        )
    else:
        saver = args.checkpoint_dir and _is_checkpoint_writer()
    global _ckpt_writer
    if saver and args.checkpoint_mode == "async" and not plocal_io:
        # Gang-wide collective saves would run their XLA-collective
        # barriers on the writer thread — the exact deadlock TPT201
        # bans. Degrade to synchronous saves, loudly.
        print("warning: async checkpointing requires process-local IO "
              "(jax.distributed client); multi-process runtime without "
              "one — falling back to --checkpoint-mode sync",
              file=sys.stderr)
    if saver and args.checkpoint_mode == "async" and plocal_io:
        # Zero-stall checkpointing: the write leg of every save rides
        # this pipeline's thread. Only the saving process has one —
        # checkpoint IO is process-local (checkpoint._checkpointer scopes
        # every orbax barrier to the calling process), so non-saver gang
        # members neither enter saves nor carry a writer. Constructed
        # here (post-fork, post-distributed-init) and its thread starts
        # immediately, warming the orbax checkpointer under the model
        # build/compile.
        _ckpt_writer = _CkptWriter()
    # Digest decision keys on the writer's EXISTENCE, not the flag: an
    # async request that degraded to sync keeps the elastic-only rule.
    _digest_saves = allow_reshape or _ckpt_writer is not None

    if args.checkpoint_dir and jax.process_index() == 0 \
            and _is_checkpoint_writer():
        # A preempt/retry loop strands orbax tmp dirs (a save killed before
        # its rename) in the shared dir; sweep them before resume so disk
        # stops leaking one partial checkpoint per kill.
        from tf_operator_tpu.models import checkpoint as _ckpt_sweep

        swept = _ckpt_sweep.sweep_tmp_dirs(args.checkpoint_dir)
        if swept:
            _emit({"event": "checkpoint_tmp_swept", "entries": swept})

    from tf_operator_tpu import optim as optim_lib

    # Dtype-configurable Adam/AdamW (tf_operator_tpu/optim.py): the default
    # f32/no-master config is leaf-for-leaf checkpoint-compatible with the
    # optax.adamw state earlier rounds wrote, and parity-pinned against
    # optax by tests/test_optimizer.py.
    tx = optim_lib.make_optimizer(optim_lib.OptimizerConfig(
        name=args.optimizer,
        learning_rate=args.lr,
        moment_dtype=args.moment_dtype,
        master_weights=args.master_weights,
    ))

    def build_state():
        p, ms = init_params(jax.random.key(0))
        return create_train_state(p, tx, ms)

    # One compiled program builds the fully-sharded initial state directly
    # on the mesh: out_shardings come from an eval_shape pass, so setup
    # costs a single compile+dispatch instead of one round-trip per
    # init/optimizer primitive (which dominated cold start on a tunneled
    # chip) — and params materialize already laid out, never replicated.
    st_sh = state_shardings(jax.eval_shape(build_state), mesh, rules)
    state = jax.jit(build_state, out_shardings=st_sh)()
    state, start_step = _try_resume(
        args.checkpoint_dir, state, tx, mesh=mesh,
        allow_reshape=allow_reshape,
    )
    # Shard-by-spec placement: the (possibly resharded) host tree lands
    # on the CURRENT mesh per the sharding rules — params and optimizer
    # state re-laid-out together, whatever shape the checkpoint came from.
    state = shard_state(state, mesh, rules)
    _emit({"event": "model_ready", "t": time.time()})
    # Startup liveness milestone: the resumed step is known, the first
    # (possibly long) compile is about to start — refresh the heartbeat so
    # the watchdog's staleness clock restarts here, not at process start.
    _hb(start_step, force=True)
    if start_step >= args.steps:
        # Already trained to (or past) the target: restart policies must be
        # idempotent, not retrain.
        from tf_operator_tpu.models import checkpoint as ckpt_lib

        if (saver and jax.process_index() == 0 and start_step > 0
                and ckpt_lib.final_step(args.checkpoint_dir) is None):
            ckpt_lib.mark_final(args.checkpoint_dir, start_step)
        _emit({"event": "done", "t": time.time(), "steps": start_step,
               "steady_steps_per_sec": None, "examples_per_sec": None,
               "final_loss": None, "total_s": round(time.time() - t_start, 3),
               "resumed_complete": True})
        from tf_operator_tpu.parallel.distributed import distributed_goodbye

        distributed_goodbye()
        return 0
    xla_options = dict(kv.split("=", 1) for kv in args.xla_option)
    if (args.model == "moe-lm" and args.moe_dispatch == "sparse"
            and jax.default_backend() == "tpu"):
        # lax.ragged_dot's mosaic kernel at the bench expert shapes picks a
        # 4096x768x512 tiling: ~21.5M scoped VMEM for the forward and
        # ~33.8M for the dW ragged-dot in the backward; the 16M default
        # fails the compile outright. 48M covers both with margin.
        xla_options.setdefault("xla_tpu_scoped_vmem_limit_kib", "49152")
    if ms_world is not None:
        if args.data_dir:
            raise SystemExit(
                "multi-slice training (TPUJOB_NUM_SLICES > 1) drives the "
                "synthetic on-device batch path; --data-dir is not "
                "supported yet")
        if state.model_state:
            raise SystemExit(
                f"--model {args.model} carries mutable model state "
                f"(batch stats), which does not cross the DCN exchange; "
                f"pick a stateless model for multi-slice")

        def rebuild_state():
            # A SliceRewind re-restores the shared checkpoint into a
            # FRESH state (the old one was donated into apply).
            return jax.jit(build_state, out_shardings=st_sh)()

        return _train_multislice(args, state, start_step, loss_fn, tx,
                                 mesh, rules, make_batch, rebuild_state,
                                 saver, t_start, guard, ms_world)
    if args.data_dir:
        return _train_on_dataset(args, state, start_step, loss_fn, tx, mesh,
                                 rules, saver, t_start, guard,
                                 xla_options=xla_options or None)

    compile_scanned = make_scanned_train_step(
        loss_fn, tx, mesh, make_batch, rules=rules, remat=args.remat,
        compiler_options=xla_options or None,
    )
    # Chunked on-device loop: one dispatch per `chunk` steps (batches are
    # generated inside the compiled program) — per-step host round-trips to
    # a tunneled chip otherwise dominate small-model step time. The chunk
    # honors the checkpoint cadence EXACTLY (gcd, so chunk boundaries land
    # on every multiple of checkpoint_every even when log_every doesn't
    # divide it). RNG streams key off the GLOBAL step, so a resumed run
    # reproduces the uninterrupted trajectory.
    import math

    # Chunk derives from flags only (identical on every replica): gating on
    # the local checkpoint-writer role would give chief and workers
    # different scan unrolls — divergent SPMD programs across one
    # jax.distributed job.
    chunk = max(1, min(args.log_every, args.steps - start_step))
    if args.checkpoint_dir and args.checkpoint_every:
        chunk = max(1, math.gcd(chunk, args.checkpoint_every))
    step_chunk = compile_scanned(state, chunk)
    ckpt_marks = (start_step // args.checkpoint_every) if args.checkpoint_every else 0
    last_save_s, last_ckpt_step = 0.0, -1

    def maybe_checkpoint(done: int, st=None) -> None:
        nonlocal ckpt_marks, last_save_s, last_ckpt_step
        if not (saver and args.checkpoint_every) or done >= args.steps:
            return  # the final save (marked FINAL) happens after the loop
        marks = done // args.checkpoint_every
        if marks > ckpt_marks:
            ckpt_marks = marks
            # _save_checkpoint opens its own phase (checkpoint /
            # ckpt_snapshot) and only around an ACTUAL save — the no-op
            # calls never reach it, so runs that never saved in the
            # window report no checkpoint phase.
            last_save_s = _save_checkpoint(
                args.checkpoint_dir, done, state,
                keep=args.keep_checkpoints, st=st)
            last_ckpt_step = done

    def check_boundary(done: int, st=None) -> int | None:
        """Heartbeat + chaos hang/kill-at-step + preemption handling after
        a chunk: returns the exit code to leave with, or None to continue
        training."""
        _hb(done)
        _boundary_chaos(done, start_step)
        if guard.triggered:
            return _preempt_exit(args, guard, state, done, saver,
                                 last_save_s, last_ckpt_step, st)
        return None

    state, metrics = step_chunk(state)
    # Host transfer, not block_until_ready (a no-op through the axon
    # tunnel): startup_s must include the first chunk's device execution.
    first_loss = float(metrics["loss"])
    t_first = time.time()
    done = start_step + chunk
    _emit(
        {
            "event": "first_step",
            "t": t_first,
            "startup_s": round(t_first - t_start, 3),
            "steps_in_first_call": chunk,
            "loss": first_loss,
            "mesh": dict(mesh.shape),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "n_devices": len(jax.devices()),
        }
    )
    maybe_checkpoint(done)
    rc = check_boundary(done)
    if rc is not None:
        return rc

    # Steady-state window: full chunks only (every dispatch reuses the one
    # compiled program). The tail chunk, if any, needs its own compile and
    # runs AFTER dt is captured so compilation never pollutes throughput.
    full_chunks = (args.steps - done) // chunk
    tail = (args.steps - done) % chunk
    profiling = bool(args.profile_dir) and full_chunks > 0
    # Tracing adds host/device overhead, so the profiled chunk must sit
    # OUTSIDE the throughput window: with >=2 full chunks, time the first
    # n-1 untraced and trace only the last; with a single chunk the trace
    # covers it and the throughput is marked as measured-under-profiling.
    profile_last_chunk = profiling and full_chunks >= 2
    timed_chunks = full_chunks - 1 if profile_last_chunk else full_chunks
    if profiling and not profile_last_chunk:
        _start_profile(args.profile_dir)
    # Latency-hiding progress: fetching a chunk's loss right after
    # dispatching it idles the chip for a full host<->device round trip
    # (~100 ms through the axon tunnel) every chunk. Instead, dispatch
    # chunk i+1 FIRST (donated state returns immediately as a future),
    # then fetch chunk i's loss while i+1 computes — the transfer rides
    # under compute and only the window-closing fetch blocks. Progress
    # events lag one chunk; each carries its own step number.
    # Phase accounting at chunk granularity: one dispatch covers `chunk`
    # steps, so each chunk records ONE sample weighted as `chunk` per-step
    # samples (telemetry/phases.py) — the done event's step_time_s stays a
    # per-STEP distribution whatever the dispatch granularity.
    t0 = time.time()
    pending = None  # (step count at fetch, metrics of that chunk)
    acct = telemetry.make_step_accounting()
    for _ in range(timed_chunks):
        _trace_window_check(args, done - start_step - chunk)
        with acct.step(done + chunk, n_steps=chunk) as st:
            with st.phase("dispatch"):
                state, metrics = step_chunk(state)
            done += chunk
            if pending is not None:
                pstep, pmetrics = pending
                # Throttle to the requested cadence: emitting every
                # sub-log_every chunk would reintroduce per-step round-trips.
                if pstep % args.log_every == 0:
                    with st.phase("device_blocked"):
                        ploss = float(pmetrics["loss"])
                    _emit({"event": "progress", "step": pstep, "loss": ploss})
            pending = (done, metrics)
            maybe_checkpoint(done, st)
            rc = check_boundary(done, st)
            if rc is not None:
                return rc
    if pending is not None:
        # The last chunk's fetch is the REAL window closure —
        # block_until_ready is a no-op through the axon tunnel.
        pstep, pmetrics = pending
        closing_loss = float(pmetrics["loss"])
    dt = time.time() - t0
    if pending is not None and (pstep % args.log_every == 0
                                or pstep == args.steps):
        _emit({"event": "progress", "step": pstep, "loss": closing_loss})
    steady = timed_chunks * chunk
    if profile_last_chunk:
        _start_profile(args.profile_dir)
    if profiling and not profile_last_chunk:
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": steady, "in_timed_window": True})
    if profile_last_chunk:
        state, metrics = step_chunk(state)
        done += chunk
        # Host transfer BEFORE stop_trace: block_until_ready is a no-op
        # through the axon tunnel, and stopping the trace while the chunk
        # is still executing would truncate it.
        chunk_loss = float(metrics["loss"])
        if done % args.log_every == 0 or done == args.steps:
            _emit({"event": "progress", "step": done, "loss": chunk_loss})
        jax.profiler.stop_trace()
        _emit({"event": "profile_done", "dir": args.profile_dir,
               "steps_traced": chunk, "in_timed_window": False})
        maybe_checkpoint(done)
        rc = check_boundary(done)
        if rc is not None:
            return rc

    if tail:
        state, metrics = compile_scanned(state, tail)(state)
        done += tail
        _emit({"event": "progress", "step": done,
               "loss": float(metrics["loss"])})
    if saver:
        _save_checkpoint(args.checkpoint_dir, args.steps, state, final=True,
                         keep=args.keep_checkpoints)
    # The final step must land in the heartbeat whatever the throttle did
    # at intermediate boundaries (the watchdog/collector read it back).
    _hb(args.steps, force=True)
    # With steps <= one chunk there is no steady-state window (only the
    # compile call ran); report null throughput rather than a
    # microseconds-denominator lie.
    sps = round(steady / dt, 4) if steady > 0 else None
    telem = acct.summary()
    done_event = {
        "event": "done",
        "t": time.time(),
        "steps": args.steps,
        "steady_steps_per_sec": sps,
        "examples_per_sec": round(steady * args.batch / dt, 4) if steady > 0 else None,  # 4 dp: 2-dp quantized batch-1 long-context rows by +-2.6%
        "final_loss": float(metrics["loss"]),
        "total_s": round(time.time() - t_start, 3),
        # Per-step distribution + telescoping phase breakdown over the
        # steady window (telemetry/phases.py); None when the run had
        # no steady chunks, same rule as steady_steps_per_sec.
        "step_time_s": telem["step_time_s"] if telem else None,
        "phase_breakdown": telem["phase_breakdown"] if telem else None,
    }
    ckpt_block = _ckpt_done_stats()
    if ckpt_block:
        # Zero-stall checkpointing accounting (docs/perf.md stall model):
        # the step loop paid snapshot_s (+ drain_wait_s backpressure);
        # write_s rode the writer thread, hidden_fraction says how much
        # of it training actually covered.
        done_event["checkpoint"] = ckpt_block
    _emit(done_event)
    _maybe_export_trace(args)
    # Synchronized multi-process exit (no-op single-process): see
    # parallel.distributed.distributed_goodbye.
    from tf_operator_tpu.parallel.distributed import distributed_goodbye

    distributed_goodbye()
    return 0


if __name__ == "__main__":
    sys.exit(main())
