"""Decode-capable TransformerLM forward with an explicit KV cache.

The serving decode loop (serve/server.py round 19) needs three things the
training-side `TransformerLM.apply` cannot give it:

  * PREFILL that returns the per-layer K/V it computed, so a new sequence's
    attention state can be parked in a replica-resident cache slot;
  * a single-token DECODE STEP that reads/extends that cache — O(T) work per
    generated token instead of the O(T^2) full re-forward;
  * slot-addressed cache updates, so the continuous-batching scheduler can
    admit/retire individual sequences between ticks without touching the
    others' state.

flax's mutable-cache machinery keeps the cache inside module variables; the
scheduler needs it as plain device arrays it can scatter into per slot. So
this module is a hand-written functional forward over the *same param tree*
`TransformerLM` produces — the param paths (trunk/{embed, pos_embed,
layer_i/{attn/{query,key,value,attn_out}, ln1, ln2, mlp_in, mlp_out}, ln_f},
lm_head) are the repo-wide module-name contract (sharding rules, checkpoint
census), and `tests/test_serve_decode.py` pins prefill-logit equality against
`TransformerLM.apply` so the two forwards cannot drift apart silently.

Numerics mirror the flax modules: f32 params, `cfg.dtype` (bf16 by default)
matmul compute, f32 layernorm statistics, f32 softmax, f32 final logits.

Cache layout: one (k, v) pair of [num_layers, slots, heads, max_len,
head_dim] arrays in `cfg.dtype`. The slot axis is the scheduler's unit of
admission; position `p` of slot `s` holds the K/V of the token *fed* at
absolute position p (prompt tokens from prefill, generated tokens from
decode steps). Everything here is pure and jit-friendly; the server jits
`prefill_into_slots`/`decode_step` once — with the cache buffers donated,
so slot scatters update in place — and warms them over the
(rows x seq-len) bucket grid before readiness.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import TransformerConfig
from tf_operator_tpu.ops.flash_attention import NEG_INF

ENV_NUM_HEADS = "TPUJOB_SERVE_NUM_HEADS"

# Conventional attention head width: every shape in a TransformerLM param
# tree determines vocab/hidden/layers/max_len, but the head COUNT never
# appears in any kernel shape, so serving a bare checkpoint needs a rule.
# The trainer's transformer-lm defaults (hidden 512 / 8 heads) follow it;
# non-conforming models override via TPUJOB_SERVE_NUM_HEADS.
DEFAULT_HEAD_DIM = 64


def config_from_params(params, num_heads: int | None = None
                       ) -> TransformerConfig:
    """Reconstruct the decode config from a TransformerLM param tree.

    vocab/hidden come from the embedding table, num_layers from the
    layer_i count, max_len from the position table, mlp_ratio from the
    mlp_in kernel. num_heads is NOT derivable from shapes — pass it,
    set TPUJOB_SERVE_NUM_HEADS, or inherit the head_dim=64 convention.
    """
    try:
        trunk = params["trunk"]
        vocab, hidden = trunk["embed"]["embedding"].shape
        max_len = trunk["pos_embed"]["embedding"].shape[0]
        layers = sum(1 for k in trunk if str(k).startswith("layer_"))
        mlp_ratio = (trunk["layer_0"]["mlp_in"]["kernel"].shape[1]
                     // hidden)
    except (KeyError, TypeError) as e:
        raise ValueError(
            f"param tree is not a TransformerLM checkpoint (missing "
            f"{e}): decode serving needs the trunk/lm_head layout") from None
    if num_heads is None:
        env = os.environ.get(ENV_NUM_HEADS)
        if env:
            num_heads = int(env)
        elif hidden % DEFAULT_HEAD_DIM == 0:
            num_heads = hidden // DEFAULT_HEAD_DIM
        else:
            raise ValueError(
                f"cannot infer num_heads for hidden={hidden} (not a "
                f"multiple of {DEFAULT_HEAD_DIM}); set {ENV_NUM_HEADS}")
    if hidden % num_heads:
        raise ValueError(f"num_heads {num_heads} does not divide "
                         f"hidden {hidden}")
    return TransformerConfig(
        vocab_size=vocab, num_layers=layers, hidden=hidden,
        num_heads=num_heads, mlp_ratio=mlp_ratio, max_len=max_len,
        causal=True)


def init_kv_cache(cfg: TransformerConfig, slots: int, max_len: int):
    """Zeroed (k, v) cache: [layers, slots, heads, max_len, head_dim]."""
    shape = (cfg.num_layers, slots, cfg.num_heads, max_len, cfg.head_dim)
    return jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype)


def _dense(p, x, dtype):
    y = x @ p["kernel"].astype(dtype)
    if "bias" in p:
        y = y + p["bias"].astype(dtype)
    return y


def _layernorm(p, x):
    """flax LayerNorm numerics: f32 statistics, eps 1e-6, f32 affine,
    result back in the compute dtype."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = jnp.square(xf - mu).mean(-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _split_heads(a, heads, head_dim):
    b, t, _ = a.shape
    return a.reshape(b, t, heads, head_dim).transpose(0, 2, 1, 3)


def prefill(params, tokens, lengths, cfg: TransformerConfig):
    """Full causal forward over prompt tokens, keeping per-layer K/V.

    tokens: [rows, T] int32 (zero-padded past each row's length);
    lengths: [rows] int32 — the true prompt length per row.

    Returns (k [L, rows, H, T, D], v [...], next_tokens [rows] int32,
    last_logits [rows, vocab] f32): the K/V ready to scatter into cache
    slots, plus the greedy first generated token (the logits at each
    row's LAST real position). Padding rows/positions produce garbage
    K/V past `lengths` — harmless, since decode attention masks by
    position and slot reuse overwrites from 0.
    """
    dtype = cfg.dtype
    trunk = params["trunk"]
    x = jnp.take(trunk["embed"]["embedding"], tokens, axis=0).astype(dtype)
    t = tokens.shape[1]
    pos = trunk["pos_embed"]["embedding"][:t].astype(dtype)
    x = x + pos[None]
    ks, vs = [], []
    for i in range(cfg.num_layers):
        lp = trunk[f"layer_{i}"]
        h = _layernorm(lp["ln1"], x)
        ap = lp["attn"]
        q = _split_heads(_dense(ap["query"], h, dtype), cfg.num_heads,
                         cfg.head_dim)
        k = _split_heads(_dense(ap["key"], h, dtype), cfg.num_heads,
                         cfg.head_dim)
        v = _split_heads(_dense(ap["value"], h, dtype), cfg.num_heads,
                         cfg.head_dim)
        ks.append(k)
        vs.append(v)
        # Same reference numerics as training (ring_attention's single-
        # device path): dtype QK^T, f32 softmax, dtype PV.
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
            jnp.float32(cfg.head_dim)).astype(dtype)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, NEG_INF)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(x.shape)
        x = x + _dense(ap["attn_out"], o, dtype)
        h = _dense(lp["mlp_in"], _layernorm(lp["ln2"], x), dtype)
        x = x + _dense(lp["mlp_out"], jax.nn.gelu(h), dtype)
    x = _layernorm(trunk["ln_f"], x)
    rows = tokens.shape[0]
    last = x[jnp.arange(rows), jnp.maximum(lengths - 1, 0)]
    logits = (last @ params["lm_head"]["kernel"].astype(dtype)
              ).astype(jnp.float32)
    return (jnp.stack(ks), jnp.stack(vs),
            jnp.argmax(logits, axis=-1).astype(jnp.int32), logits)


def write_slots(k_cache, v_cache, k_chunk, v_chunk, slot_ids):
    """Scatter a prefill chunk's K/V ([L, rows, H, T, D]) into cache
    slots `slot_ids` ([rows] int32) at token positions [0, T). Duplicate
    slot ids are legal (last-write-wins) — the scheduler pads short
    chunks by repeating its scratch slot."""
    t = k_chunk.shape[3]
    k_cache = k_cache.at[:, slot_ids, :, :t, :].set(k_chunk)
    v_cache = v_cache.at[:, slot_ids, :, :t, :].set(v_chunk)
    return k_cache, v_cache


def prefill_into_slots(params, k_cache, v_cache, tokens, lengths,
                       slot_ids, cfg: TransformerConfig):
    """Fused prefill + slot scatter: ONE dispatch per admission chunk.

    The scheduler admits between decode ticks, so admission cost is paid
    on the serving critical path; fusing also lets the server jit this
    with the cache buffers DONATED (in-place update — the cache is
    several MB per replica and would otherwise be copied whole on every
    admission).

    Returns (k_cache, v_cache, next_tokens [rows] int32,
    last_logits [rows, vocab] f32)."""
    k_chunk, v_chunk, next_tokens, logits = prefill(params, tokens,
                                                    lengths, cfg)
    k_cache, v_cache = write_slots(k_cache, v_cache, k_chunk, v_chunk,
                                   slot_ids)
    return k_cache, v_cache, next_tokens, logits


def decode_step(params, k_cache, v_cache, tokens, positions,
                cfg: TransformerConfig):
    """One decode tick over every cache slot.

    tokens: [slots] int32 — the token FED to each slot this tick (its
    K/V lands at `positions`); positions: [slots] int32 absolute
    positions. Attention for slot s covers cached positions <=
    positions[s], so inactive slots' stale state is never read once the
    scheduler re-prefills on reuse.

    Returns (k_cache, v_cache, next_tokens [slots] int32,
    logits [slots, vocab] f32).
    """
    dtype = cfg.dtype
    trunk = params["trunk"]
    slots = tokens.shape[0]
    max_len = k_cache.shape[3]
    x = jnp.take(trunk["embed"]["embedding"], tokens, axis=0).astype(dtype)
    x = x + jnp.take(trunk["pos_embed"]["embedding"], positions,
                     axis=0).astype(dtype)  # [S, H*D]
    s_i = jnp.arange(slots)
    visible = (jnp.arange(max_len)[None] <= positions[:, None])  # [S, ML]
    for i in range(cfg.num_layers):
        lp = trunk[f"layer_{i}"]
        h = _layernorm(lp["ln1"], x)
        ap = lp["attn"]

        def heads(a):  # [S, hidden] -> [S, H, D]
            return a.reshape(slots, cfg.num_heads, cfg.head_dim)

        q = heads(_dense(ap["query"], h, dtype))
        k_tok = heads(_dense(ap["key"], h, dtype))
        v_tok = heads(_dense(ap["value"], h, dtype))
        # Scatter this tick's K/V at each slot's own position.
        k_cache = k_cache.at[i, s_i, :, positions, :].set(k_tok)
        v_cache = v_cache.at[i, s_i, :, positions, :].set(v_tok)
        k_l, v_l = k_cache[i], v_cache[i]  # [S, H, ML, D]
        s = jnp.einsum("shd,shmd->shm", q, k_l) / jnp.sqrt(
            jnp.float32(cfg.head_dim)).astype(dtype)
        s = jnp.where(visible[:, None, :], s, NEG_INF)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dtype)
        o = jnp.einsum("shm,shmd->shd", p, v_l).reshape(slots, cfg.hidden)
        x = x + _dense(ap["attn_out"], o, dtype)
        h = _dense(lp["mlp_in"], _layernorm(lp["ln2"], x), dtype)
        x = x + _dense(lp["mlp_out"], jax.nn.gelu(h), dtype)
    x = _layernorm(trunk["ln_f"], x)
    logits = (x @ params["lm_head"]["kernel"].astype(dtype)
              ).astype(jnp.float32)
    return (k_cache, v_cache,
            jnp.argmax(logits, axis=-1).astype(jnp.int32), logits)
