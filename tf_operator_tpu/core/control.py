"""Pod/Service control: create/delete with events + owner-ref stamping.

Capability parity with pkg/control/{pod_control,service_control}.go: every
create/delete goes through one chokepoint that (a) stamps the controller
owner reference, (b) records a K8s-style Event (Events double as a test
assertion surface, ref pod_control.go:139-148), (c) reports failure without
raising so the reconciler can keep going and rely on requeue.
"""

from __future__ import annotations

from tf_operator_tpu.api.types import OwnerReference
from tf_operator_tpu.core.cluster import (
    ApiError,
    InMemoryCluster,
    Pod,
    Service,
)

EVENT_SUCCESSFUL_CREATE_POD = "SuccessfulCreatePod"
EVENT_FAILED_CREATE_POD = "FailedCreatePod"
EVENT_SUCCESSFUL_DELETE_POD = "SuccessfulDeletePod"
EVENT_FAILED_DELETE_POD = "FailedDeletePod"
EVENT_SUCCESSFUL_CREATE_SERVICE = "SuccessfulCreateService"
EVENT_FAILED_CREATE_SERVICE = "FailedCreateService"
EVENT_SUCCESSFUL_DELETE_SERVICE = "SuccessfulDeleteService"
EVENT_FAILED_DELETE_SERVICE = "FailedDeleteService"


def gen_owner_reference(job) -> OwnerReference:
    """Controller ownership marker (ref GenOwnerReference, jobcontroller.go:198).
    Kind-generic: `job` is any owner object carrying KIND/API_VERSION
    class attributes (TrainJob, InferenceService)."""
    return OwnerReference(
        api_version=job.API_VERSION,
        kind=job.KIND,
        name=job.name,
        uid=job.uid,
        controller=True,
        block_owner_deletion=True,
    )


class PodControl:
    def __init__(self, cluster: InMemoryCluster):
        self.cluster = cluster

    def create_pod(self, pod: Pod, job) -> bool:
        pod.metadata.owner_references = [gen_owner_reference(job)]
        try:
            self.cluster.create_pod(pod)
        except ApiError as e:
            self.cluster.record_event(
                job.KIND, job.namespace, job.name, "Warning",
                EVENT_FAILED_CREATE_POD, f"Error creating pod {pod.name}: {e}",
            )
            return False
        self.cluster.record_event(
            job.KIND, job.namespace, job.name, "Normal",
            EVENT_SUCCESSFUL_CREATE_POD, f"Created pod: {pod.name}",
        )
        return True

    def delete_pod(self, namespace: str, name: str, job) -> bool:
        try:
            self.cluster.delete_pod(namespace, name)
        except ApiError as e:
            self.cluster.record_event(
                job.KIND, job.namespace, job.name, "Warning",
                EVENT_FAILED_DELETE_POD, f"Error deleting pod {name}: {e}",
            )
            return False
        self.cluster.record_event(
            job.KIND, job.namespace, job.name, "Normal",
            EVENT_SUCCESSFUL_DELETE_POD, f"Deleted pod: {name}",
        )
        return True


class ServiceControl:
    def __init__(self, cluster: InMemoryCluster):
        self.cluster = cluster

    def create_service(self, svc: Service, job) -> bool:
        svc.metadata.owner_references = [gen_owner_reference(job)]
        try:
            self.cluster.create_service(svc)
        except ApiError as e:
            self.cluster.record_event(
                job.KIND, job.namespace, job.name, "Warning",
                EVENT_FAILED_CREATE_SERVICE, f"Error creating service {svc.name}: {e}",
            )
            return False
        self.cluster.record_event(
            job.KIND, job.namespace, job.name, "Normal",
            EVENT_SUCCESSFUL_CREATE_SERVICE, f"Created service: {svc.name}",
        )
        return True

    def delete_service(self, namespace: str, name: str, job) -> bool:
        try:
            self.cluster.delete_service(namespace, name)
        except ApiError as e:
            self.cluster.record_event(
                job.KIND, job.namespace, job.name, "Warning",
                EVENT_FAILED_DELETE_SERVICE, f"Error deleting service {name}: {e}",
            )
            return False
        self.cluster.record_event(
            job.KIND, job.namespace, job.name, "Normal",
            EVENT_SUCCESSFUL_DELETE_SERVICE, f"Deleted service: {name}",
        )
        return True
