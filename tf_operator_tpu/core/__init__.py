"""Reconcile core: cluster substrate, workqueue, expectations, controllers."""
