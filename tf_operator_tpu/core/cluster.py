"""The cluster substrate: an in-process API server + informer bus.

The reference talks to a Kubernetes API server through generated clientsets
and shared informers (SURVEY.md §1 L2/L3). This framework's equivalent is a
pluggable `Cluster` substrate holding the same object kinds (jobs, pods,
services, pod groups, events) with:

  - CRUD with optimistic resource versions and AlreadyExists/NotFound errors
  - label-selector listing (the slice of selector algebra the operator uses)
  - synchronous add/update/delete handlers per kind — the informer-event
    contract the controllers consume (ref jobcontroller.go:81-138 handlers)
  - an Event recorder doubling as a test assertion surface (ref
    control/pod_control.go:139-148; E2E get_creation_failures_from_tfjob)

`InMemoryCluster` is simultaneously the Tier-1 test fake (tests set pod
phases directly, like testutil.SetPodsStatuses) and the real substrate for
the local-process runtime, which materialises pods as OS processes and feeds
their exit codes back into pod status. A future backend can adapt the same
interface to a real K8s API server.
"""

from __future__ import annotations

import copy
import itertools
import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable

from tf_operator_tpu.api.types import ObjectMeta, OwnerReference, PodTemplateSpec, TrainJob


class PodPhase(str, Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"

    def __str__(self) -> str:
        return self.value


@dataclass
class ContainerStatus:
    name: str
    running: bool = False
    exit_code: int | None = None
    reason: str = ""
    restart_count: int = 0


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    start_time: float | None = None
    message: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta
    spec: PodTemplateSpec
    status: PodStatus = field(default_factory=PodStatus)
    node_name: str = ""
    scheduler_name: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def controller_ref(self) -> OwnerReference | None:
        for ref in self.metadata.owner_references:
            if ref.controller:
                return ref
        return None

    def is_finished(self) -> bool:
        return self.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)

    def main_exit_code(self, container_name: str | None = None) -> int | None:
        """Exit code of the training container (ref pod.go:137-146 pulls the
        tensorflow container's terminated state)."""
        for cs in self.status.container_statuses:
            if container_name is None or cs.name == container_name:
                if cs.exit_code is not None:
                    return cs.exit_code
        return None


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0


@dataclass
class Service:
    metadata: ObjectMeta
    selector: dict[str, str] = field(default_factory=dict)
    ports: list[ServicePort] = field(default_factory=list)
    cluster_ip: str = "None"  # headless: stable DNS, no VIP (ref service.go:98-109)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def controller_ref(self) -> OwnerReference | None:
        for ref in self.metadata.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass
class PodGroup:
    """Gang-scheduling unit (ref SyncPodGroup, jobcontroller.go:226-250)."""

    metadata: ObjectMeta
    min_member: int = 0
    queue: str = ""
    priority_class: str = ""
    # TPU twist: a pod group may pin an atomic slice allocation.
    tpu_topology: str = ""

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


@dataclass
class Event:
    kind: str
    namespace: str
    name: str
    type: str  # "Normal" | "Warning"
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)


class ApiError(Exception):
    pass


class NotFoundError(ApiError):
    pass


class GoneError(ApiError):
    """HTTP 410 / watch-ERROR code 410: requested resourceVersion was
    compacted out of server history — the only correct recovery is a fresh
    LIST (client-go reflector's relist-on-Gone)."""


class AlreadyExistsError(ApiError):
    pass


class ConflictError(ApiError):
    pass


Handler = Callable[[Any], None]
UpdateHandler = Callable[[Any, Any], None]

KIND_JOB = "TrainJob"
KIND_INFSVC = "InferenceService"
KIND_POD = "Pod"
KIND_SERVICE = "Service"
KIND_PODGROUP = "PodGroup"

# Published by the node agent on each pod it runs: the replica's dialable
# local HTTP address (this framework's stand-in for status.podIP). The
# dashboard's endpoints view reads it back when no in-process runtime is
# attached.
ENDPOINT_ANNOTATION = "tpujob.dev/host-endpoint"


class InMemoryCluster:
    """Thread-safe in-process cluster state with informer-style handlers.

    Handlers are invoked synchronously after the mutation commits, outside the
    store lock (so handlers may call back into the API). Objects are deep-
    copied on the way in and out: callers never share mutable state with the
    store, matching API-server value semantics (the reference relies on
    DeepCopy before mutation, controller.go:312)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._stores: dict[str, dict[tuple[str, str], Any]] = {
            KIND_JOB: {},
            KIND_INFSVC: {},
            KIND_POD: {},
            KIND_SERVICE: {},
            KIND_PODGROUP: {},
        }
        self._events: list[Event] = []
        self._rv = itertools.count(1)
        self._add_handlers: dict[str, list[Handler]] = {}
        self._update_handlers: dict[str, list[UpdateHandler]] = {}
        self._delete_handlers: dict[str, list[Handler]] = {}

    # ---- handler registration (informer contract) ----

    def on_add(self, kind: str, fn: Handler) -> None:
        with self._lock:
            self._add_handlers.setdefault(kind, []).append(fn)

    def on_update(self, kind: str, fn: UpdateHandler) -> None:
        with self._lock:
            self._update_handlers.setdefault(kind, []).append(fn)

    def on_delete(self, kind: str, fn: Handler) -> None:
        with self._lock:
            self._delete_handlers.setdefault(kind, []).append(fn)

    def _fire_add(self, kind: str, obj: Any) -> None:
        for fn in list(self._add_handlers.get(kind, [])):
            fn(copy.deepcopy(obj))

    def _fire_update(self, kind: str, old: Any, new: Any) -> None:
        for fn in list(self._update_handlers.get(kind, [])):
            fn(copy.deepcopy(old), copy.deepcopy(new))

    def _fire_delete(self, kind: str, obj: Any) -> None:
        for fn in list(self._delete_handlers.get(kind, [])):
            fn(copy.deepcopy(obj))

    # ---- generic CRUD ----

    def _create(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = (obj.metadata.namespace, obj.metadata.name)
            if key in self._stores[kind]:
                raise AlreadyExistsError(f"{kind} {key[0]}/{key[1]} already exists")
            if not obj.metadata.uid:
                obj.metadata.uid = str(uuid.uuid4())
            obj.metadata.resource_version = next(self._rv)
            stored = copy.deepcopy(obj)
            self._stores[kind][key] = stored
        self._fire_add(kind, stored)
        return copy.deepcopy(stored)

    def _get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            obj = self._stores[kind].get((namespace, name))
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj)

    def _try_get(self, kind: str, namespace: str, name: str) -> Any | None:
        try:
            return self._get(kind, namespace, name)
        except NotFoundError:
            return None

    def _update(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = (obj.metadata.namespace, obj.metadata.name)
            old = self._stores[kind].get(key)
            if old is None:
                raise NotFoundError(f"{kind} {key[0]}/{key[1]} not found")
            obj.metadata.resource_version = next(self._rv)
            stored = copy.deepcopy(obj)
            self._stores[kind][key] = stored
        self._fire_update(kind, old, stored)
        return copy.deepcopy(stored)

    def _delete(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            obj = self._stores[kind].pop((namespace, name), None)
            if obj is None:
                raise NotFoundError(f"{kind} {namespace}/{name} not found")
        self._fire_delete(kind, obj)
        return obj

    def _list(self, kind: str, namespace: str | None, selector: dict[str, str] | None) -> list[Any]:
        with self._lock:
            out = []
            for (ns, _), obj in self._stores[kind].items():
                if namespace is not None and ns != namespace:
                    continue
                if selector and not self._matches(obj.metadata.labels, selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    @staticmethod
    def _matches(labels: dict[str, str], selector: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in selector.items())

    # ---- jobs ----

    def create_job(self, job: TrainJob) -> TrainJob:
        return self._create(KIND_JOB, job)

    def get_job(self, namespace: str, name: str) -> TrainJob:
        return self._get(KIND_JOB, namespace, name)

    def try_get_job(self, namespace: str, name: str, *,
                    read_through: bool = False) -> TrainJob | None:
        del read_through  # every read here is read-through already
        return self._try_get(KIND_JOB, namespace, name)

    def update_job(self, job: TrainJob) -> TrainJob:
        return self._update(KIND_JOB, job)

    def update_job_status(self, job: TrainJob, *, expected_rv=None,
                          base=None) -> TrainJob:
        """Status-subresource write: only .status (+ bookkeeping annotations)
        are persisted from `job` (ref UpdateStatus, k8sutil/client.go:85).

        Round 17 extensions (status_writer.py is the caller):
        `expected_rv` fences the write against the resourceVersion the
        caller OBSERVED — a mismatch raises ConflictError instead of
        blindly overwriting a newer status (the lister-snapshot staleness
        guard). A write that would change nothing is skipped entirely
        (no rv bump, no handler fire) so level-triggered no-op syncs are
        invisible to watchers; `base` is accepted for signature parity
        with the K8s substrate, which cannot read the stored object for
        free — here the store itself is the diff baseline.
        """
        del base
        with self._lock:
            key = (job.metadata.namespace, job.metadata.name)
            old = self._stores[KIND_JOB].get(key)
            if old is None:
                raise NotFoundError(f"TrainJob {key[0]}/{key[1]} not found")
            if (expected_rv is not None
                    and old.metadata.resource_version != expected_rv):
                raise ConflictError(
                    f"TrainJob {key[0]}/{key[1]}: resourceVersion "
                    f"{expected_rv} != {old.metadata.resource_version}")
            if (job.status == old.status
                    and dict(job.metadata.annotations)
                    == dict(old.metadata.annotations)):
                return copy.deepcopy(old)
            new = copy.deepcopy(old)
            new.status = copy.deepcopy(job.status)
            new.metadata.annotations = dict(job.metadata.annotations)
            new.metadata.resource_version = next(self._rv)
            self._stores[KIND_JOB][key] = new
        self._fire_update(KIND_JOB, old, new)
        return copy.deepcopy(new)

    def delete_job(self, namespace: str, name: str) -> TrainJob:
        return self._delete(KIND_JOB, namespace, name)

    def list_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        return self._list(KIND_JOB, namespace, None)

    def snapshot_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        """Read-only lister snapshot (round 17): the stored objects
        themselves, NO deep copies — the same contract as K8sCluster's
        informer-cache snapshot. For scans that only inspect (resync
        enqueue, slice-waiter kicks), where list_jobs' full deep copy is
        O(fleet) allocation per wave. Callers must not mutate."""
        with self._lock:
            return [o for (ns, _), o in self._stores[KIND_JOB].items()
                    if namespace is None or ns == namespace]

    # ---- inference services (the second workload kind; same CRUD shape
    # ---- as jobs, including the status-subresource write semantics) ----

    def create_infsvc(self, svc) -> Any:
        return self._create(KIND_INFSVC, svc)

    def get_infsvc(self, namespace: str, name: str) -> Any:
        return self._get(KIND_INFSVC, namespace, name)

    def try_get_infsvc(self, namespace: str, name: str) -> Any | None:
        return self._try_get(KIND_INFSVC, namespace, name)

    def update_infsvc(self, svc) -> Any:
        return self._update(KIND_INFSVC, svc)

    def update_infsvc_status(self, svc, *, expected_rv=None,
                             base=None) -> Any:
        """Same contract as update_job_status, including the round-17
        rv fence and the no-op skip (both workload kinds optimize
        together or neither — the PR-13 review note)."""
        del base
        with self._lock:
            key = (svc.metadata.namespace, svc.metadata.name)
            old = self._stores[KIND_INFSVC].get(key)
            if old is None:
                raise NotFoundError(
                    f"InferenceService {key[0]}/{key[1]} not found")
            if (expected_rv is not None
                    and old.metadata.resource_version != expected_rv):
                raise ConflictError(
                    f"InferenceService {key[0]}/{key[1]}: resourceVersion "
                    f"{expected_rv} != {old.metadata.resource_version}")
            if (svc.status == old.status
                    and dict(svc.metadata.annotations)
                    == dict(old.metadata.annotations)):
                return copy.deepcopy(old)
            new = copy.deepcopy(old)
            new.status = copy.deepcopy(svc.status)
            new.metadata.annotations = dict(svc.metadata.annotations)
            new.metadata.resource_version = next(self._rv)
            self._stores[KIND_INFSVC][key] = new
        self._fire_update(KIND_INFSVC, old, new)
        return copy.deepcopy(new)

    def delete_infsvc(self, namespace: str, name: str) -> Any:
        return self._delete(KIND_INFSVC, namespace, name)

    def list_infsvcs(self, namespace: str | None = None) -> list[Any]:
        return self._list(KIND_INFSVC, namespace, None)

    def snapshot_infsvcs(self, namespace: str | None = None) -> list[Any]:
        """Read-only lister snapshot (see snapshot_jobs)."""
        with self._lock:
            return [o for (ns, _), o in self._stores[KIND_INFSVC].items()
                    if namespace is None or ns == namespace]

    # ---- pods ----

    def create_pod(self, pod: Pod) -> Pod:
        return self._create(KIND_POD, pod)

    def get_pod(self, namespace: str, name: str) -> Pod:
        return self._get(KIND_POD, namespace, name)

    def try_get_pod(self, namespace: str, name: str) -> Pod | None:
        return self._try_get(KIND_POD, namespace, name)

    def update_pod(self, pod: Pod) -> Pod:
        return self._update(KIND_POD, pod)

    def update_pod_status(self, pod: Pod) -> Pod:
        """Kubelet-side write (status + runtime annotations). One store on
        the in-memory substrate; the K8s adapter splits it across the main
        resource and the /status subresource."""
        return self._update(KIND_POD, pod)

    def delete_pod(self, namespace: str, name: str) -> Pod:
        return self._delete(KIND_POD, namespace, name)

    def list_pods(
        self, namespace: str | None = None, selector: dict[str, str] | None = None
    ) -> list[Pod]:
        return self._list(KIND_POD, namespace, selector)

    def set_pod_phase(
        self,
        namespace: str,
        name: str,
        phase: PodPhase,
        exit_code: int | None = None,
        restart_count: int | None = None,
        container: str = "tensorflow",
    ) -> Pod:
        """Test/runtime helper: mutate a pod's status (kubelet stand-in)."""
        pod = self.get_pod(namespace, name)
        pod.status.phase = phase
        if pod.status.start_time is None and phase != PodPhase.PENDING:
            pod.status.start_time = time.time()
        cs = None
        for c in pod.status.container_statuses:
            if c.name == container:
                cs = c
        if cs is None:
            cs = ContainerStatus(name=container)
            pod.status.container_statuses.append(cs)
        cs.running = phase == PodPhase.RUNNING
        if exit_code is not None:
            cs.exit_code = exit_code
        if restart_count is not None:
            cs.restart_count = restart_count
        return self.update_pod(pod)

    # ---- services ----

    def create_service(self, svc: Service) -> Service:
        return self._create(KIND_SERVICE, svc)

    def get_service(self, namespace: str, name: str) -> Service:
        return self._get(KIND_SERVICE, namespace, name)

    def update_service(self, svc: Service) -> Service:
        return self._update(KIND_SERVICE, svc)

    def delete_service(self, namespace: str, name: str) -> Service:
        return self._delete(KIND_SERVICE, namespace, name)

    def list_services(
        self, namespace: str | None = None, selector: dict[str, str] | None = None
    ) -> list[Service]:
        return self._list(KIND_SERVICE, namespace, selector)

    # ---- pod groups ----

    def create_podgroup(self, pg: PodGroup) -> PodGroup:
        return self._create(KIND_PODGROUP, pg)

    def try_get_podgroup(self, namespace: str, name: str) -> PodGroup | None:
        return self._try_get(KIND_PODGROUP, namespace, name)

    def update_podgroup(self, pg: PodGroup) -> PodGroup:
        return self._update(KIND_PODGROUP, pg)

    def delete_podgroup(self, namespace: str, name: str) -> PodGroup:
        return self._delete(KIND_PODGROUP, namespace, name)

    def list_podgroups(self, namespace: str | None = None) -> list[PodGroup]:
        return self._list(KIND_PODGROUP, namespace, None)

    # ---- events ----

    def record_event(
        self, kind: str, namespace: str, name: str, etype: str, reason: str, message: str
    ) -> None:
        with self._lock:
            self._events.append(Event(kind, namespace, name, etype, reason, message))

    def events_for(self, kind: str, namespace: str, name: str) -> list[Event]:
        with self._lock:
            return [
                e
                for e in self._events
                if e.kind == kind and e.namespace == namespace and e.name == name
            ]

    def all_events(self) -> list[Event]:
        with self._lock:
            return list(self._events)
