"""Coalesced, dirty-tracked status writes (round 17).

At 10k jobs the apiserver write path is where the control plane folds
first: each reconcile used to issue up to two merge-patches (bookkeeping
annotations, then the FULL status wire form) at every one of ~7 call
sites, even when nothing changed since the last observation. The fleet
bench measured ~5 status writes per job lifecycle, most of them inside a
sub-second admitted -> running -> succeeded burst.

`StatusWriter` is the single chokepoint both workload controllers flush
through instead:

  * **Dirty tracking** — a sync starts from a pristine deep copy of the
    observed object (`base`); flush compares the working copy's status
    and annotations against it and a no-op sync issues ZERO apiserver
    requests. The substrate (`update_job_status(job, base=...)`) then
    diffs the wire form per top-level status key, so a real write ships
    only what changed — not the whole ~15-key status document.

  * **Burst coalescing (opt-in)** — with `window > 0`, a non-urgent
    dirty flush is DEFERRED: the writer requeues the key for
    `window` seconds after its first un-flushed dirtiness and writes
    nothing now. The next sync recomputes the same diff against the
    then-current observation (deferred dirt is recomputed, never
    stored), so the queued/admitted/running transitions of a fast job
    merge into its one terminal write. THE CONTRACT this places on
    callers: every non-urgent status/annotation mutation must be a
    pure function of state the deferred sync can RE-OBSERVE (the
    object itself, its pods/services, scheduler state). A value
    derived from transient observed state — say a counter sampled
    from a pod condition that may vanish before the deferred sync
    fires — would be silently LOST, not coalesced; such writes must
    flush `urgent=True` (which is exactly why the durability latches
    do). `window=0` (default) flushes every dirty sync — bit-for-bit
    today's write timing, which tests observe. Urgent flushes
    (terminal conditions, durability latches that must be persisted
    before pod deletions, reshape records) always write immediately
    and also sweep up any deferred dirt.

  * **Generation fencing** — when the controller read the object from a
    lister snapshot (`lists_from_cache`), flush carries the observed
    resourceVersion as a merge-patch precondition. A stale snapshot
    then 409s on flush instead of blindly overwriting a newer status;
    the conflict propagates to the workqueue's rate-limited requeue and
    the resync converges once the informer catches up. Read-through
    substrates skip the fence so the merge-patch lane stays
    conflict-free against concurrent spec editors (the PUT-vs-editor
    fight test_k8s pins).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from tf_operator_tpu.status import metrics
from tf_operator_tpu.telemetry import journal as _journal
from tf_operator_tpu.telemetry import tracer as _tracer

# Padding added to the deferral requeue so the follow-up sync lands just
# AFTER the window expires (landing just before would defer once more and
# double the effective latency).
_DEFER_SLACK_S = 0.05


class StatusWriter:
    """Per-controller coalescing flush front-end for one workload kind.

    Thread-safe: syncs for different keys run on different workqueue
    shards of the same controller instance concurrently; per-key state
    (the first-dirty timestamp) is guarded. Per-key ordering is the
    workqueue's own guarantee (same key -> same shard).
    """

    def __init__(
        self,
        update_fn: Callable[..., Any],
        *,
        kind: str,
        window: float = 0.0,
        clock: Callable[[], float] = time.time,
        defer: Callable[[str, float], None] | None = None,
        fence: bool = False,
    ) -> None:
        self._update = update_fn  # cluster.update_{job,infsvc}_status
        self.kind = kind
        self.window = float(window)
        self._clock = clock
        self._defer = defer  # (key, delay_s) -> requeue for a later sync
        self.fence = fence
        self._lock = threading.Lock()
        # key -> when the key FIRST went dirty without being flushed; the
        # deferral deadline is first + window (not last + window, which
        # would let a steadily-mutating job defer forever).
        self._first_dirty: dict[str, float] = {}

    @staticmethod
    def dirty(obj: Any, base: Any) -> bool:
        """Did this sync change anything a status write would persist?"""
        return (obj.status != base.status
                or dict(obj.metadata.annotations)
                != dict(base.metadata.annotations))

    def flush(self, obj: Any, base: Any, *, urgent: bool = False,
              reconcile_id: int = 0) -> Any:
        """Write obj's status+annotations if they differ from `base`
        (the pristine observed copy this sync started from). Returns the
        post-write object (or `obj` unchanged when nothing was written).

        A deferred non-urgent flush (window > 0) writes NOTHING and
        retains no diff — the deferred sync recomputes dirt from its own
        fresh observation. Non-urgent mutations must therefore be pure
        functions of re-observable state; anything derived from
        transient state must pass urgent=True or it can be lost (see
        the module docstring's coalescing contract).

        Raises the substrate's ConflictError when the fence detects the
        observation was stale — callers let it propagate so the
        workqueue's error path requeues the key.
        """
        key = f"{obj.metadata.namespace}/{obj.metadata.name}"
        jrnl = _journal.get_journal()
        if not self.dirty(obj, base):
            with self._lock:
                self._first_dirty.pop(key, None)
            metrics.status_writes_coalesced.labels(
                kind=self.kind, reason="noop").inc()
            jrnl.record(key, "status.flush", reconcile_id, outcome="noop")
            return obj
        if not urgent and self.window > 0:
            now = self._clock()
            with self._lock:
                first = self._first_dirty.setdefault(key, now)
            remaining = first + self.window - now
            if remaining > 0:
                if self._defer is not None:
                    self._defer(key, remaining + _DEFER_SLACK_S)
                metrics.status_writes_coalesced.labels(
                    kind=self.kind, reason="deferred").inc()
                jrnl.record(key, "status.flush", reconcile_id,
                            outcome="deferred")
                return obj
        with self._lock:
            self._first_dirty.pop(key, None)
        expected_rv = (base.metadata.resource_version
                       if self.fence else None)
        try:
            with _tracer.span("status.flush", job=key, kind=self.kind,
                              urgent=urgent):
                out = self._update(obj, expected_rv=expected_rv, base=base)
        except Exception:
            # The fence tripped (stale lister snapshot 409'd) or the
            # apiserver rejected the write — journal it so a timeline
            # shows the retry loop, then let the workqueue's error path
            # requeue as before.
            jrnl.record(key, "status.flush", reconcile_id,
                        outcome="fenced" if expected_rv is not None
                        else "error", urgent=urgent)
            raise
        jrnl.record(key, "status.flush", reconcile_id, outcome="sent",
                    urgent=urgent)
        return out

    def forget(self, key: str) -> None:
        """Drop per-key deferral state (the object was deleted)."""
        with self._lock:
            self._first_dirty.pop(key, None)

    def pending(self) -> dict[str, float]:
        """Keys with un-flushed deferred dirt -> when each FIRST went
        dirty (/debug/state visibility into the coalescing window)."""
        with self._lock:
            return dict(self._first_dirty)
