"""Generic job-controller framework.

Capability parity with pkg/common/jobcontroller/ (SURVEY.md §1 L4): the
reusable, framework-agnostic base the reference exposed as
`ControllerInterface` + `JobController` so PyTorch/MXNet operators could
share one reconcile engine. Here the plug-point is the abstract methods of
`JobControllerBase`; `TrainJobController` (trainjob_controller.py) is the
TrainJob implementation.

Responsibilities at this layer (ref jobcontroller.go:81-301, pod.go, service.go):
  - informer event handlers: pod/service add/update/delete -> resolve the
    owning job via controller ref -> expectation bookkeeping -> enqueue key
  - rate-limited workqueue worker loop
  - label generation and label-selector based claim/adopt of pods & services
  - index-sliced replica views (GetPodSlices)
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable

from tf_operator_tpu.api.types import TrainJob
from tf_operator_tpu.core.cluster import (
    KIND_POD,
    KIND_SERVICE,
    InMemoryCluster,
    Pod,
    Service,
)
from tf_operator_tpu.core.control import PodControl, ServiceControl
from tf_operator_tpu.core.expectations import make_expectations
from tf_operator_tpu.core.workqueue import make_queue
from tf_operator_tpu.telemetry import journal as _journal
from tf_operator_tpu.telemetry import tracer as _tracer
from tf_operator_tpu.utils import naming
from tf_operator_tpu.utils.logging import logger_for_key

# Label vocabulary (ref jobcontroller.go GenLabels + pod.go:187-193).
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
# Topology fingerprint stamped at pod creation (cluster_spec.tf_config.
# topology_hash); a live pod whose label mismatches the job's current hash
# is rolled so its injected TF_CONFIG/TPU env matches the spec (elastic
# scaling — beyond the reference, SURVEY §5 "No elasticity").
LABEL_SPEC_HASH = "spec-hash"
# Multi-slice jobs (spec.tpu.slices > 1): which per-slice gang this pod
# belongs to — the granularity per-slice recovery rolls at and chaos
# `slice=K` targeting matches against.
LABEL_SLICE_ID = "slice-id"


def gen_labels(job_name: str) -> dict[str, str]:
    return {
        LABEL_GROUP_NAME: TrainJob.API_GROUP,
        LABEL_JOB_NAME: job_name.replace("/", "-"),
    }


# Slice-claim keys of serving replicas are `{ns}/{name}#r{i}` — the "#"
# marks a per-replica sub-claim of an InferenceService, so capacity kicks
# and preemption targets route to the owning kind's controller (the part
# before "#" is the service's sync key). TrainJob keys never contain "#".
CLAIM_SEP = "#"


def claim_owner_key(key: str) -> str:
    """The sync key that owns a scheduler claim key (identity for plain
    job keys; the service key for `ns/name#rI` serve-replica claims)."""
    return key.split(CLAIM_SEP, 1)[0]


def make_enqueue_router(train_controller_ref, serve_controller_ref):
    """THE cross-kind enqueue router (one definition, shared by
    cmd_operator and LocalSession): scheduler kick targets and preemption
    victims dispatch to whichever controller owns the key — serve-replica
    claims carry CLAIM_SEP and collapse to their service's sync key,
    everything else is a TrainJob key. The refs are one-element lists so
    the router can be handed to the first controller's constructor before
    the second controller exists."""
    def route(key: str) -> None:
        if CLAIM_SEP in key and serve_controller_ref:
            serve_controller_ref[0].enqueue(claim_owner_key(key))
        elif train_controller_ref:
            train_controller_ref[0].enqueue(key)
    return route


class JobControllerBase:
    """Reconcile engine: workqueue + expectations + claim/adopt.

    Kind-generic (the reference's ControllerInterface promise, made
    real): `OWNER_KIND` plus the three owner accessors below are the
    whole per-kind surface — TrainJobController keeps the defaults,
    serve/controller.py's InferenceServiceController overrides them.
    """

    # The owner kind this controller reconciles: informer registration,
    # controller-ref resolution, and claim/adopt all key on it.
    OWNER_KIND = TrainJob.KIND

    def __init__(self, cluster: InMemoryCluster, queue_shards: int = 1,
                 enqueue_router=None):
        self.cluster = cluster
        # Cross-kind enqueue routing: with two controllers sharing one
        # scheduler/allocator, a freed slice's kick targets (and
        # preemption victims) may belong to the OTHER kind — the router
        # (make_enqueue_router above) dispatches each key to the
        # controller that owns it. None = route to our own queue
        # (single-kind deployments, tests).
        self.enqueue_router = enqueue_router
        # queue_shards > 1: fleet-scale mode — keys route to stable shards
        # and each worker thread services its own (core/workqueue.py
        # ShardedRateLimitingQueue), so reconcile workers stop contending
        # on one queue lock under thousands of jobs.
        self.queue = make_queue(shards=queue_shards)
        self.expectations = make_expectations()
        self.pod_control = PodControl(cluster)
        self.service_control = ServiceControl(cluster)
        self._stop = threading.Event()
        self._workers: list[threading.Thread] = []
        self._in_flight = 0
        self._idle_cond = threading.Condition()
        # Sync-wave ids for the flight recorder: one id per _process_item
        # pass; every journal event recorded on the sync's thread — by this
        # controller, the scheduler it consults, or the StatusWriter it
        # flushes through — carries it, so a timeline groups by wave.
        self._reconcile_ids = itertools.count(1)
        self._register_handlers()

    # ---- plug-points (ControllerInterface, jobcontroller.go:33-63) ----

    def sync_job(self, key: str) -> None:
        raise NotImplementedError

    def _try_get_owner(self, namespace: str, name: str):
        """The owner object this controller reconciles (None if gone)."""
        return self.cluster.try_get_job(namespace, name)

    def _list_owners(self) -> list:
        """Resync scan. A read-only lister snapshot, NOT a deep-copying
        LIST: resync only reads keys (round 17 — at 10k jobs the old
        full-LIST-the-world was the resync's dominant cost)."""
        return self.cluster.snapshot_jobs()

    def _owner_replica_types(self, obj) -> list[str]:
        """Replica-type strings the owner's expectations are keyed by."""
        return [str(rt) for rt in obj.spec.replica_specs]

    # ---- informer wiring ----

    def _register_handlers(self) -> None:
        self.cluster.on_add(self.OWNER_KIND, self._on_job_add)
        self.cluster.on_update(self.OWNER_KIND, self._on_job_update)
        self.cluster.on_delete(self.OWNER_KIND, self._on_job_delete)
        self.cluster.on_add(KIND_POD, self._on_pod_add)
        self.cluster.on_update(KIND_POD, self._on_pod_update)
        self.cluster.on_delete(KIND_POD, self._on_pod_delete)
        self.cluster.on_add(KIND_SERVICE, self._on_service_add)
        self.cluster.on_update(KIND_SERVICE, self._on_service_update)
        self.cluster.on_delete(KIND_SERVICE, self._on_service_delete)

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def route_enqueue(self, key: str) -> None:
        """Enqueue a key that may belong to ANOTHER kind's controller
        (scheduler kick targets, preemption victims). Serve-replica claim
        keys collapse to their owning service key either way."""
        if self.enqueue_router is not None:
            self.enqueue_router(key)
        else:
            self.enqueue(claim_owner_key(key))

    def _on_job_add(self, job: TrainJob) -> None:
        self.enqueue(job.key())

    def _on_job_update(self, old: TrainJob, new: TrainJob) -> None:
        self.enqueue(new.key())

    def _on_job_delete(self, job) -> None:
        key = job.key()
        for rtype in self._owner_replica_types(job):
            self.expectations.delete_expectations(
                naming.gen_expectation_pods_key(key, rtype)
            )
            self.expectations.delete_expectations(
                naming.gen_expectation_services_key(key, rtype)
            )
        self.queue.forget(key)
        # Cascade deletion: the reference relied on the K8s garbage collector
        # following ownerReferences (blockOwnerDeletion); this substrate IS
        # the API server, so the controller collects the children itself.
        # Cascade failures are expected (delete races: the object may be
        # gone by the time we get there) but must not vanish — tpulint
        # TPH101: a broad except hiding a real apiserver error here would
        # leak every child of every deleted job, silently.
        log = logger_for_key(key)
        for pod in self.cluster.list_pods(job.namespace, gen_labels(job.name)):
            ref = pod.controller_ref()
            if ref is not None and ref.uid == job.uid:
                try:
                    self.cluster.delete_pod(pod.namespace, pod.name)
                except Exception as e:
                    log.info("cascade pod delete %s: %s", pod.name, e)
        for svc in self.cluster.list_services(job.namespace, gen_labels(job.name)):
            ref = svc.controller_ref()
            if ref is not None and ref.uid == job.uid:
                try:
                    self.cluster.delete_service(svc.namespace, svc.name)
                except Exception as e:
                    log.info("cascade service delete %s: %s", svc.name, e)
        pg = self.cluster.try_get_podgroup(job.namespace, job.name)
        if pg is not None:
            try:
                self.cluster.delete_podgroup(job.namespace, job.name)
            except Exception as e:
                log.info("cascade podgroup delete: %s", e)
        # One final sync of the now-missing key releases slice allocations
        # and expectation entries (sync_job's not-found path).
        self.enqueue(key)

    def _owner_key(self, obj: Pod | Service) -> tuple[str, str] | None:
        """(owner_key, replica_type) for an object owned by one of our
        owners (ref resolveControllerRef, jobcontroller/pod.go:20-67)."""
        ref = obj.controller_ref()
        if ref is None or ref.kind != self.OWNER_KIND:
            return None
        job = self._try_get_owner(obj.metadata.namespace, ref.name)
        if job is None or (ref.uid and job.uid and job.uid != ref.uid):
            return None
        rtype = obj.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        return naming.job_key(job.namespace, job.name), rtype

    def _on_pod_add(self, pod: Pod) -> None:
        owner = self._owner_key(pod)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.creation_observed(naming.gen_expectation_pods_key(key, rtype))
        self.enqueue(key)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if old.metadata.resource_version == new.metadata.resource_version:
            return
        owner = self._owner_key(new)
        if owner is not None:
            self.enqueue(owner[0])

    def _on_pod_delete(self, pod: Pod) -> None:
        owner = self._owner_key(pod)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.deletion_observed(naming.gen_expectation_pods_key(key, rtype))
        self.enqueue(key)

    def _on_service_add(self, svc: Service) -> None:
        owner = self._owner_key(svc)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.creation_observed(
            naming.gen_expectation_services_key(key, rtype)
        )
        self.enqueue(key)

    def _on_service_update(self, old: Service, new: Service) -> None:
        # Parity note: the reference leaves service update/delete as TODO
        # no-ops (service.go:58-66); we at least re-enqueue the owner.
        owner = self._owner_key(new)
        if owner is not None:
            self.enqueue(owner[0])

    def _on_service_delete(self, svc: Service) -> None:
        # Unlike the reference's TODO no-op (service.go:58-66), deletions are
        # observed: elastic scale-down raises service-delete expectations,
        # and an unobserved expectation would gate the next sync until the
        # 5-minute expectation timeout.
        owner = self._owner_key(svc)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.deletion_observed(
            naming.gen_expectation_services_key(key, rtype)
        )
        self.enqueue(key)

    # ---- claim/adopt (ref ClaimPods/ClaimServices + ref managers) ----

    def get_pods_for_job(self, job) -> list[Pod]:
        selector = gen_labels(job.name)
        pods = self.cluster.list_pods(job.namespace, selector)
        return self._claim(pods, job, self.cluster.update_pod)

    def get_services_for_job(self, job) -> list[Service]:
        selector = gen_labels(job.name)
        services = self.cluster.list_services(job.namespace, selector)
        return self._claim(services, job, self.cluster.update_service)

    # ---- tracked create/delete (expectation bookkeeping chokepoints) ----
    #
    # Factored from the TrainJob controller (round 17): the raise-
    # expectation / act / roll-back-on-failure dance appeared at every
    # call site and is identical for both workload kinds.

    def _tracked_delete_pod(self, owner, pod: Pod) -> None:
        rt = pod.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        exp_key = naming.gen_expectation_pods_key(owner.key(), rt)
        self.expectations.raise_expectations(exp_key, 0, 1)
        if not self.pod_control.delete_pod(pod.namespace, pod.name, owner):
            self.expectations.deletion_observed(exp_key)
        else:
            _journal.get_journal().record(owner.key(), "pod.delete",
                                          pod=pod.name)

    def _tracked_delete_service(self, owner, svc: Service) -> None:
        rt = svc.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        exp_key = naming.gen_expectation_services_key(owner.key(), rt)
        self.expectations.raise_expectations(exp_key, 0, 1)
        if not self.service_control.delete_service(
                svc.namespace, svc.name, owner):
            self.expectations.deletion_observed(exp_key)

    def _tracked_create_pod(self, owner, pod: Pod, rtype: str) -> bool:
        exp_key = naming.gen_expectation_pods_key(owner.key(), rtype)
        self.expectations.raise_expectations(exp_key, 1, 0)
        if not self.pod_control.create_pod(pod, owner):
            # Creation failed: lower the expectation so the owner isn't
            # stuck until the 5-minute expectation timeout.
            self.expectations.creation_observed(exp_key)
            return False
        _journal.get_journal().record(owner.key(), "pod.create",
                                      pod=pod.name, replica_type=rtype)
        return True

    def _tracked_create_service(self, owner, svc: Service,
                                rtype: str) -> bool:
        exp_key = naming.gen_expectation_services_key(owner.key(), rtype)
        self.expectations.raise_expectations(exp_key, 1, 0)
        if not self.service_control.create_service(svc, owner):
            self.expectations.creation_observed(exp_key)
            return False
        return True

    def _delete_out_of_range(
        self, owner, objs, replicas: int, exp_key: str, delete_fn,
        event_reason: str | None = None,
    ) -> None:
        """Delete pods/services whose replica-index is >= the current
        count (elastic/autoscale scale-down), with delete-expectation
        bookkeeping. Shared by both workload kinds."""
        for obj in objs:
            try:
                idx = int(obj.metadata.labels.get(LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if idx < replicas:
                continue
            if event_reason:
                self.cluster.record_event(
                    self.OWNER_KIND, owner.namespace, owner.name, "Normal",
                    event_reason,
                    f"Deleting {obj.name}: index {idx} >= {replicas} "
                    f"replicas",
                )
            self.expectations.raise_expectations(exp_key, 0, 1)
            if not delete_fn(obj.metadata.namespace, obj.name, owner):
                self.expectations.deletion_observed(exp_key)

    def _claim(self, objs, job, updater: Callable | None):
        """Keep objects our controller ref owns; adopt label-matching orphans
        (ref service_ref_manager.go:83-160). Objects owned by another
        controller are left alone."""
        from tf_operator_tpu.core.control import gen_owner_reference

        claimed = []
        for obj in objs:
            ref = obj.controller_ref()
            if ref is not None:
                if ref.uid == job.uid:
                    claimed.append(obj)
                continue
            # Orphan with matching labels: adopt unless job is being deleted.
            if job.metadata.deletion_timestamp is None:
                obj.metadata.owner_references.append(gen_owner_reference(job))
                if updater is not None:
                    obj = updater(obj)
                claimed.append(obj)
        return claimed

    @staticmethod
    def filter_pods_for_replica_type(pods: list[Pod], rtype: str) -> list[Pod]:
        return [p for p in pods if p.metadata.labels.get(LABEL_REPLICA_TYPE) == rtype.lower()]

    @staticmethod
    def get_pod_slices(pods: list[Pod], replicas: int) -> list[list[Pod]]:
        """Index-sliced view: slices[i] = pods labeled replica-index=i
        (ref GetPodSlices, jobcontroller/pod.go:222)."""
        slices: list[list[Pod]] = [[] for _ in range(replicas)]
        for p in pods:
            try:
                idx = int(p.metadata.labels.get(LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if 0 <= idx < replicas:
                slices[idx].append(p)
        return slices

    @staticmethod
    def filter_services_for_replica_type(services: list[Service], rtype: str) -> list[Service]:
        return [s for s in services if s.metadata.labels.get(LABEL_REPLICA_TYPE) == rtype.lower()]

    @staticmethod
    def get_service_slices(services: list[Service], replicas: int) -> list[list[Service]]:
        slices: list[list[Service]] = [[] for _ in range(replicas)]
        for s in services:
            try:
                idx = int(s.metadata.labels.get(LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if 0 <= idx < replicas:
                slices[idx].append(s)
        return slices

    # ---- worker loop (ref controller.go:182-270) ----

    def run(self, workers: int = 1) -> None:
        self._stop.clear()
        # Initial resync: owners that existed before this controller was
        # constructed (operator restart, late leader) must still reconcile —
        # informer handlers only cover future events (WaitForCacheSync +
        # initial-list parity, controller.go:192).
        for job in self._list_owners():
            self.enqueue(job.key())
        for i in range(workers):
            t = threading.Thread(
                target=self._worker, args=(i,), name=f"reconciler-{i}",
                daemon=True,
            )
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        for t in self._workers:
            t.join(timeout=5)
        self._workers.clear()

    def _process_item(self, item) -> None:
        """Sync one key; on failure, requeue with backoff (controller.go:267)."""
        from tf_operator_tpu.status import metrics

        # One sync wave = one reconcile_id: stamp the thread so every
        # journal event this pass emits (controller, scheduler, status
        # writer) is causally groupable, and open an operator trace span
        # (no-op unless the operator ran with --trace).
        rid = next(self._reconcile_ids)
        jrnl = _journal.get_journal()
        jrnl.set_wave(rid)
        t0 = time.monotonic()
        try:
            with _tracer.span("reconcile", job=str(item), kind=self.OWNER_KIND,
                              reconcile_id=rid):
                self.sync_job(item)
            self.queue.forget(item)
        except Exception as e:
            metrics.reconcile_errors.inc()
            logger_for_key(str(item)).error("sync failed: %s: %s", type(e).__name__, e)
            self.queue.add_rate_limited(item)
        finally:
            # Sync-latency distribution (the reference logs this per pass,
            # controller.go:289-291; we expose it on /metrics).
            metrics.reconcile_latency.observe(time.monotonic() - t0)
            self.queue.done(item)
            jrnl.set_wave(0)

    def _worker(self, index: int = 0) -> None:
        sharded = getattr(self.queue, "sharded", False)
        while not self._stop.is_set():
            if sharded:
                item = self.queue.get(timeout=0.2, shard=index)
            else:
                item = self.queue.get(timeout=0.2)
            if item is None:
                continue
            with self._idle_cond:
                self._in_flight += 1
            try:
                self._process_item(item)
            finally:
                with self._idle_cond:
                    self._in_flight -= 1
                    self._idle_cond.notify_all()

    def run_until_idle(self, timeout: float = 10.0) -> bool:
        """Test/E2E helper: process queued work until the queue drains.
        Returns False on timeout. Delayed items (add_after) are NOT waited
        for — idle means 'nothing ready now'."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            item = self.queue.get(timeout=0.05)
            if item is None:
                with self._idle_cond:
                    if self._in_flight == 0 and len(self.queue) == 0:
                        return True
                continue
            self._process_item(item)
        return False
