"""Generic job-controller framework.

Capability parity with pkg/common/jobcontroller/ (SURVEY.md §1 L4): the
reusable, framework-agnostic base the reference exposed as
`ControllerInterface` + `JobController` so PyTorch/MXNet operators could
share one reconcile engine. Here the plug-point is the abstract methods of
`JobControllerBase`; `TrainJobController` (trainjob_controller.py) is the
TrainJob implementation.

Responsibilities at this layer (ref jobcontroller.go:81-301, pod.go, service.go):
  - informer event handlers: pod/service add/update/delete -> resolve the
    owning job via controller ref -> expectation bookkeeping -> enqueue key
  - rate-limited workqueue worker loop
  - label generation and label-selector based claim/adopt of pods & services
  - index-sliced replica views (GetPodSlices)
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from tf_operator_tpu.api.types import TrainJob
from tf_operator_tpu.core.cluster import (
    KIND_POD,
    KIND_SERVICE,
    KIND_JOB,
    InMemoryCluster,
    Pod,
    Service,
)
from tf_operator_tpu.core.control import PodControl, ServiceControl
from tf_operator_tpu.core.expectations import make_expectations
from tf_operator_tpu.core.workqueue import make_queue
from tf_operator_tpu.utils import naming
from tf_operator_tpu.utils.logging import logger_for_key

# Label vocabulary (ref jobcontroller.go GenLabels + pod.go:187-193).
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
# Topology fingerprint stamped at pod creation (cluster_spec.tf_config.
# topology_hash); a live pod whose label mismatches the job's current hash
# is rolled so its injected TF_CONFIG/TPU env matches the spec (elastic
# scaling — beyond the reference, SURVEY §5 "No elasticity").
LABEL_SPEC_HASH = "spec-hash"
# Multi-slice jobs (spec.tpu.slices > 1): which per-slice gang this pod
# belongs to — the granularity per-slice recovery rolls at and chaos
# `slice=K` targeting matches against.
LABEL_SLICE_ID = "slice-id"


def gen_labels(job_name: str) -> dict[str, str]:
    return {
        LABEL_GROUP_NAME: TrainJob.API_GROUP,
        LABEL_JOB_NAME: job_name.replace("/", "-"),
    }


class JobControllerBase:
    """Reconcile engine: workqueue + expectations + claim/adopt."""

    def __init__(self, cluster: InMemoryCluster, queue_shards: int = 1):
        self.cluster = cluster
        # queue_shards > 1: fleet-scale mode — keys route to stable shards
        # and each worker thread services its own (core/workqueue.py
        # ShardedRateLimitingQueue), so reconcile workers stop contending
        # on one queue lock under thousands of jobs.
        self.queue = make_queue(shards=queue_shards)
        self.expectations = make_expectations()
        self.pod_control = PodControl(cluster)
        self.service_control = ServiceControl(cluster)
        self._stop = threading.Event()
        self._workers: list[threading.Thread] = []
        self._in_flight = 0
        self._idle_cond = threading.Condition()
        self._register_handlers()

    # ---- plug-points (ControllerInterface, jobcontroller.go:33-63) ----

    def sync_job(self, key: str) -> None:
        raise NotImplementedError

    # ---- informer wiring ----

    def _register_handlers(self) -> None:
        self.cluster.on_add(KIND_JOB, self._on_job_add)
        self.cluster.on_update(KIND_JOB, self._on_job_update)
        self.cluster.on_delete(KIND_JOB, self._on_job_delete)
        self.cluster.on_add(KIND_POD, self._on_pod_add)
        self.cluster.on_update(KIND_POD, self._on_pod_update)
        self.cluster.on_delete(KIND_POD, self._on_pod_delete)
        self.cluster.on_add(KIND_SERVICE, self._on_service_add)
        self.cluster.on_update(KIND_SERVICE, self._on_service_update)
        self.cluster.on_delete(KIND_SERVICE, self._on_service_delete)

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def _on_job_add(self, job: TrainJob) -> None:
        self.enqueue(job.key())

    def _on_job_update(self, old: TrainJob, new: TrainJob) -> None:
        self.enqueue(new.key())

    def _on_job_delete(self, job: TrainJob) -> None:
        key = job.key()
        for rtype in job.spec.replica_specs:
            self.expectations.delete_expectations(
                naming.gen_expectation_pods_key(key, str(rtype))
            )
            self.expectations.delete_expectations(
                naming.gen_expectation_services_key(key, str(rtype))
            )
        self.queue.forget(key)
        # Cascade deletion: the reference relied on the K8s garbage collector
        # following ownerReferences (blockOwnerDeletion); this substrate IS
        # the API server, so the controller collects the children itself.
        # Cascade failures are expected (delete races: the object may be
        # gone by the time we get there) but must not vanish — tpulint
        # TPH101: a broad except hiding a real apiserver error here would
        # leak every child of every deleted job, silently.
        log = logger_for_key(key)
        for pod in self.cluster.list_pods(job.namespace, gen_labels(job.name)):
            ref = pod.controller_ref()
            if ref is not None and ref.uid == job.uid:
                try:
                    self.cluster.delete_pod(pod.namespace, pod.name)
                except Exception as e:
                    log.info("cascade pod delete %s: %s", pod.name, e)
        for svc in self.cluster.list_services(job.namespace, gen_labels(job.name)):
            ref = svc.controller_ref()
            if ref is not None and ref.uid == job.uid:
                try:
                    self.cluster.delete_service(svc.namespace, svc.name)
                except Exception as e:
                    log.info("cascade service delete %s: %s", svc.name, e)
        pg = self.cluster.try_get_podgroup(job.namespace, job.name)
        if pg is not None:
            try:
                self.cluster.delete_podgroup(job.namespace, job.name)
            except Exception as e:
                log.info("cascade podgroup delete: %s", e)
        # One final sync of the now-missing key releases slice allocations
        # and expectation entries (sync_job's not-found path).
        self.enqueue(key)

    def _owner_key(self, obj: Pod | Service) -> tuple[str, str] | None:
        """(job_key, replica_type) for an object owned by one of our jobs
        (ref resolveControllerRef, jobcontroller/pod.go:20-67)."""
        ref = obj.controller_ref()
        if ref is None or ref.kind != TrainJob.KIND:
            return None
        job = self.cluster.try_get_job(obj.metadata.namespace, ref.name)
        if job is None or (ref.uid and job.uid and job.uid != ref.uid):
            return None
        rtype = obj.metadata.labels.get(LABEL_REPLICA_TYPE, "")
        return naming.job_key(job.namespace, job.name), rtype

    def _on_pod_add(self, pod: Pod) -> None:
        owner = self._owner_key(pod)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.creation_observed(naming.gen_expectation_pods_key(key, rtype))
        self.enqueue(key)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if old.metadata.resource_version == new.metadata.resource_version:
            return
        owner = self._owner_key(new)
        if owner is not None:
            self.enqueue(owner[0])

    def _on_pod_delete(self, pod: Pod) -> None:
        owner = self._owner_key(pod)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.deletion_observed(naming.gen_expectation_pods_key(key, rtype))
        self.enqueue(key)

    def _on_service_add(self, svc: Service) -> None:
        owner = self._owner_key(svc)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.creation_observed(
            naming.gen_expectation_services_key(key, rtype)
        )
        self.enqueue(key)

    def _on_service_update(self, old: Service, new: Service) -> None:
        # Parity note: the reference leaves service update/delete as TODO
        # no-ops (service.go:58-66); we at least re-enqueue the owner.
        owner = self._owner_key(new)
        if owner is not None:
            self.enqueue(owner[0])

    def _on_service_delete(self, svc: Service) -> None:
        # Unlike the reference's TODO no-op (service.go:58-66), deletions are
        # observed: elastic scale-down raises service-delete expectations,
        # and an unobserved expectation would gate the next sync until the
        # 5-minute expectation timeout.
        owner = self._owner_key(svc)
        if owner is None:
            return
        key, rtype = owner
        self.expectations.deletion_observed(
            naming.gen_expectation_services_key(key, rtype)
        )
        self.enqueue(key)

    # ---- claim/adopt (ref ClaimPods/ClaimServices + ref managers) ----

    def get_pods_for_job(self, job: TrainJob) -> list[Pod]:
        selector = gen_labels(job.name)
        pods = self.cluster.list_pods(job.namespace, selector)
        return self._claim(pods, job, self.cluster.update_pod)

    def get_services_for_job(self, job: TrainJob) -> list[Service]:
        selector = gen_labels(job.name)
        services = self.cluster.list_services(job.namespace, selector)
        return self._claim(services, job, self.cluster.update_service)

    def _claim(self, objs, job: TrainJob, updater: Callable | None):
        """Keep objects our controller ref owns; adopt label-matching orphans
        (ref service_ref_manager.go:83-160). Objects owned by another
        controller are left alone."""
        from tf_operator_tpu.core.control import gen_owner_reference

        claimed = []
        for obj in objs:
            ref = obj.controller_ref()
            if ref is not None:
                if ref.uid == job.uid:
                    claimed.append(obj)
                continue
            # Orphan with matching labels: adopt unless job is being deleted.
            if job.metadata.deletion_timestamp is None:
                obj.metadata.owner_references.append(gen_owner_reference(job))
                if updater is not None:
                    obj = updater(obj)
                claimed.append(obj)
        return claimed

    @staticmethod
    def filter_pods_for_replica_type(pods: list[Pod], rtype: str) -> list[Pod]:
        return [p for p in pods if p.metadata.labels.get(LABEL_REPLICA_TYPE) == rtype.lower()]

    @staticmethod
    def get_pod_slices(pods: list[Pod], replicas: int) -> list[list[Pod]]:
        """Index-sliced view: slices[i] = pods labeled replica-index=i
        (ref GetPodSlices, jobcontroller/pod.go:222)."""
        slices: list[list[Pod]] = [[] for _ in range(replicas)]
        for p in pods:
            try:
                idx = int(p.metadata.labels.get(LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if 0 <= idx < replicas:
                slices[idx].append(p)
        return slices

    @staticmethod
    def filter_services_for_replica_type(services: list[Service], rtype: str) -> list[Service]:
        return [s for s in services if s.metadata.labels.get(LABEL_REPLICA_TYPE) == rtype.lower()]

    @staticmethod
    def get_service_slices(services: list[Service], replicas: int) -> list[list[Service]]:
        slices: list[list[Service]] = [[] for _ in range(replicas)]
        for s in services:
            try:
                idx = int(s.metadata.labels.get(LABEL_REPLICA_INDEX, ""))
            except ValueError:
                continue
            if 0 <= idx < replicas:
                slices[idx].append(s)
        return slices

    # ---- worker loop (ref controller.go:182-270) ----

    def run(self, workers: int = 1) -> None:
        self._stop.clear()
        # Initial resync: jobs that existed before this controller was
        # constructed (operator restart, late leader) must still reconcile —
        # informer handlers only cover future events (WaitForCacheSync +
        # initial-list parity, controller.go:192).
        for job in self.cluster.list_jobs():
            self.enqueue(job.key())
        for i in range(workers):
            t = threading.Thread(
                target=self._worker, args=(i,), name=f"reconciler-{i}",
                daemon=True,
            )
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()
        for t in self._workers:
            t.join(timeout=5)
        self._workers.clear()

    def _process_item(self, item) -> None:
        """Sync one key; on failure, requeue with backoff (controller.go:267)."""
        from tf_operator_tpu.status import metrics

        t0 = time.monotonic()
        try:
            self.sync_job(item)
            self.queue.forget(item)
        except Exception as e:
            metrics.reconcile_errors.inc()
            logger_for_key(str(item)).error("sync failed: %s: %s", type(e).__name__, e)
            self.queue.add_rate_limited(item)
        finally:
            # Sync-latency distribution (the reference logs this per pass,
            # controller.go:289-291; we expose it on /metrics).
            metrics.reconcile_latency.observe(time.monotonic() - t0)
            self.queue.done(item)

    def _worker(self, index: int = 0) -> None:
        sharded = getattr(self.queue, "sharded", False)
        while not self._stop.is_set():
            if sharded:
                item = self.queue.get(timeout=0.2, shard=index)
            else:
                item = self.queue.get(timeout=0.2)
            if item is None:
                continue
            with self._idle_cond:
                self._in_flight += 1
            try:
                self._process_item(item)
            finally:
                with self._idle_cond:
                    self._in_flight -= 1
                    self._idle_cond.notify_all()

    def run_until_idle(self, timeout: float = 10.0) -> bool:
        """Test/E2E helper: process queued work until the queue drains.
        Returns False on timeout. Delayed items (add_after) are NOT waited
        for — idle means 'nothing ready now'."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            item = self.queue.get(timeout=0.05)
            if item is None:
                with self._idle_cond:
                    if self._in_flight == 0 and len(self.queue) == 0:
                        return True
                continue
            self._process_item(item)
        return False
