"""Rate-limited, deduplicating work queue.

Semantics match client-go's workqueue, which the reference builds its hot loop
on (ref jobcontroller.go:128-133, controller.go:198-270):

  - **Dedup**: an item added while queued coalesces to one entry.
  - **In-flight exclusivity**: an item re-added while being processed is not
    handed to a second worker; it re-queues when `done()` is called. This is
    the property that makes one-job-at-a-time reconciliation safe with many
    workers.
  - **Per-item exponential backoff** (`add_rate_limited`): 5ms * 2^failures,
    capped at 1000s, reset by `forget()` — client-go's
    DefaultControllerRateLimiter shape.
  - **Overall token bucket**: 10 qps / burst 100 across all rate-limited adds.
  - **Delayed adds** (`add_after`): the delaying queue used for TTL GC and
    ActiveDeadline re-syncs (ref job.go:136-152).

A C++ implementation of the same interface lives in native/ (runtime.native);
this pure-Python one is the always-available fallback and the reference for
its behavior.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Hashable


class ItemExponentialFailureRateLimiter:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Hashable) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2**n), self.max_delay)

    def forget(self, item: Hashable) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class BucketRateLimiter:
    """Token bucket: qps refill, burst capacity. Returns the wait time."""

    def __init__(self, qps: float = 10.0, burst: int = 100):
        self.qps = qps
        self.burst = burst
        self._tokens = float(burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def when(self, item: Hashable = None) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            need = 1.0 - self._tokens
            self._tokens -= 1.0
            return need / self.qps

    def forget(self, item: Hashable = None) -> None:
        pass

    def num_requeues(self, item: Hashable = None) -> int:
        return 0


class MaxOfRateLimiter:
    def __init__(self, *limiters: Any):
        self.limiters = limiters

    def when(self, item: Hashable) -> float:
        return max(rl.when(item) for rl in self.limiters)

    def forget(self, item: Hashable) -> None:
        for rl in self.limiters:
            rl.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return max(rl.num_requeues(item) for rl in self.limiters)


def default_rate_limiter() -> MaxOfRateLimiter:
    return MaxOfRateLimiter(
        ItemExponentialFailureRateLimiter(0.005, 1000.0), BucketRateLimiter(10.0, 100)
    )


def make_queue(rate_limiter: Any | None = None, shards: int = 1):
    """Preferred queue for string-keyed controllers: the native (C++)
    implementation when the library is available, else this module's
    pure-Python one. A custom rate_limiter forces the Python path.
    `shards` > 1 returns a ShardedRateLimitingQueue (always pure Python:
    sharding exists to spread the queue's one lock across worker threads,
    which the single native queue cannot do)."""
    if shards > 1:
        return ShardedRateLimitingQueue(shards, rate_limiter_factory=(
            (lambda: rate_limiter) if rate_limiter is not None else None))
    if rate_limiter is None:
        try:
            from tf_operator_tpu.native import NativeRateLimitingQueue

            return NativeRateLimitingQueue()  # type: ignore[return-value]
        except (ImportError, RuntimeError):
            pass
    return RateLimitingQueue(rate_limiter)


class RateLimitingQueue:
    def __init__(self, rate_limiter: Any | None = None):
        self._rl = rate_limiter or default_rate_limiter()
        self._cond = threading.Condition()
        self._queue: list[Hashable] = []  # FIFO of ready items
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._waiting: list[tuple[float, int, Hashable]] = []  # (ready_at, seq, item)
        self._seq = 0
        self._shutdown = False

    # -- core add/get/done (client-go Type semantics) --

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)
                self._cond.notify()

    def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._waiting, (time.monotonic() + delay, self._seq, item))
            self._cond.notify()

    def add_rate_limited(self, item: Hashable) -> None:
        self.add_after(item, self._rl.when(item))

    def forget(self, item: Hashable) -> None:
        self._rl.forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._rl.num_requeues(item)

    def _drain_ready(self) -> None:
        now = time.monotonic()
        while self._waiting and self._waiting[0][0] <= now:
            _, _, item = heapq.heappop(self._waiting)
            if item not in self._dirty:
                self._dirty.add(item)
                if item not in self._processing:
                    self._queue.append(item)

    def get(self, timeout: float | None = None) -> Hashable | None:
        """Blocks until an item is available; None on timeout or shutdown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                self._drain_ready()
                if self._queue:
                    item = self._queue.pop(0)
                    self._dirty.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    return None
                wait = None
                if self._waiting:
                    wait = max(0.0, self._waiting[0][0] - time.monotonic())
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)


class ShardedRateLimitingQueue:
    """N independent RateLimitingQueues behind one interface.

    Scale-out refactor for fleet-sized control planes (ISSUE 7): with
    thousands of jobs, every reconcile worker contends on the single
    queue's one Condition — adds from informer handlers, gets from
    workers, delayed drains all serialize. Sharding routes each key to a
    stable shard (crc32 — NOT the process-seeded hash(), so routing is
    identical across operator restarts and test runs), and each worker
    thread services its own shard (`get(shard=i)`), so the hot path takes
    one uncontended lock.

    Correctness properties carry over because all of client-go's queue
    semantics are PER-KEY: a key always lands on the same shard, so
    dedup, in-flight exclusivity, and per-item backoff behave exactly as
    the single queue — two keys on different shards were always allowed
    to proceed concurrently.

    `get()` without a shard scans all shards (tests / run_until_idle);
    workers pass their index for affinity. A worker whose own shard is
    empty steals one scan of the others before blocking, so a lone busy
    shard cannot idle the rest of the pool.
    """

    sharded = True

    def __init__(self, shards: int = 2, rate_limiter_factory=None):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        factory = rate_limiter_factory or (lambda: None)
        self.shards = [RateLimitingQueue(factory()) for _ in range(shards)]
        self._n = shards
        self._shutdown = False

    def shard_of(self, item: Hashable) -> int:
        import zlib

        return zlib.crc32(str(item).encode()) % self._n

    def _q(self, item: Hashable) -> RateLimitingQueue:
        return self.shards[self.shard_of(item)]

    def add(self, item: Hashable) -> None:
        self._q(item).add(item)

    def add_after(self, item: Hashable, delay: float) -> None:
        self._q(item).add_after(item, delay)

    def add_rate_limited(self, item: Hashable) -> None:
        self._q(item).add_rate_limited(item)

    def forget(self, item: Hashable) -> None:
        self._q(item).forget(item)

    def num_requeues(self, item: Hashable) -> int:
        return self._q(item).num_requeues(item)

    def done(self, item: Hashable) -> None:
        self._q(item).done(item)

    def get(self, timeout: float | None = None,
            shard: int | None = None) -> Hashable | None:
        """With `shard`, block on that shard alone after one non-blocking
        steal-scan of the others; without, poll every shard fairly until
        an item is ready or the timeout lapses."""
        if shard is not None:
            own = self.shards[shard % self._n]
            item = own.get(timeout=0)
            if item is not None:
                return item
            for i in range(self._n):
                if i != shard % self._n:
                    item = self.shards[i].get(timeout=0)
                    if item is not None:
                        return item
            return own.get(timeout=timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            for q in self.shards:
                item = q.get(timeout=0)
                if item is not None:
                    return item
            if self._shutdown:
                return None
            if deadline is not None and time.monotonic() >= deadline:
                return None
            time.sleep(0.002)

    def shut_down(self) -> None:
        self._shutdown = True
        for q in self.shards:
            q.shut_down()

    def __len__(self) -> int:
        return sum(len(q) for q in self.shards)
