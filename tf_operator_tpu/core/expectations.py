"""Controller expectations cache.

The informer-lag dance (SURVEY.md §7 "hard parts"): after the controller
issues N pod creations, the informer cache won't reflect them immediately; a
re-sync in that window would double-create. The expectations cache records
"I expect to observe N adds / M deletes for key K" and the event handlers
decrement it; `satisfied()` gates reconciliation (ref jobcontroller.go:110-126,
controller.go:477-496, modeled on k8s controller.ControllerExpectations).

Expectations expire after 5 minutes (k8s ExpectationsTimeout) so a lost event
can't wedge a job forever.
"""

from __future__ import annotations

import threading
import time

EXPECTATIONS_TIMEOUT_S = 5 * 60.0


def make_expectations() -> "ControllerExpectations":
    """Native (C++) expectations cache when available, else pure Python."""
    try:
        from tf_operator_tpu.native import NativeControllerExpectations

        return NativeControllerExpectations()  # type: ignore[return-value]
    except (ImportError, RuntimeError):
        return ControllerExpectations()


class _Entry:
    __slots__ = ("adds", "dels", "timestamp")

    def __init__(self, adds: int, dels: int):
        self.adds = adds
        self.dels = dels
        self.timestamp = time.monotonic()

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self) -> bool:
        return time.monotonic() - self.timestamp > EXPECTATIONS_TIMEOUT_S


class ControllerExpectations:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}

    def expect_creations(self, key: str, n: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(n, 0)

    def expect_deletions(self, key: str, n: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(0, n)

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._entries[key] = _Entry(adds, dels)
            else:
                e.adds += adds
                e.dels += dels

    def creation_observed(self, key: str) -> None:
        self._lower(key, 1, 0)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, 0, 1)

    def _lower(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.adds -= adds
                e.dels -= dels

    def satisfied(self, key: str) -> bool:
        """True if expectations are fulfilled, expired, or never set — the
        exact gate of k8s SatisfiedExpectations."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return True
            return e.fulfilled() or e.expired()

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)
