"""The TrainJob controller: reconcile desired replica state into pods/services.

Capability parity with pkg/controller.v1/tensorflow/ (SURVEY.md §1 L5, §3.2-3.3):
  - syncTFJob/reconcileTFJobs orchestration     (controller.go:286-471)
  - per-replica pod diffing + creation          (pod.go:89-330)
  - headless service per replica                (service.go:35-128)
  - terminal handling: cleanPodPolicy, TTL GC,
    backoffLimit, activeDeadlineSeconds         (job.go:155-219, controller.go:371-438)
  - exit-code restart semantics                 (pod.go:135-156 + train_util.go)
  - gang scheduling + atomic TPU-slice admission(jobcontroller.go:226, pod.go:224-238)
  - fork behaviors preserved: default TTLs (900s only when cleanPodPolicy=All
    and the job did not fail, else 7d debug TTL — job.go:181-219), failed jobs
    keep their pods for debugging (job.go:162), `((index))` subPath
    substitution for per-replica data shards (pod.go:50-85)

TPU-native deltas:
  - pods get the JAX/TPU cluster contract (cluster_spec.tpu_env) in addition
    to legacy TF_CONFIG; SPMD pods get `google.com/tpu` resources
  - gang admission is whole-slice: a job requesting `tpu.topology` only gets
    pods once a free slice of that shape exists (SliceAllocator)
"""

from __future__ import annotations

import copy
import time

from tf_operator_tpu.api import defaults as api_defaults
from tf_operator_tpu.api import validation as api_validation
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    TrainJob,
    has_condition,
    is_failed,
    is_terminal,
)
from tf_operator_tpu.cluster_spec import tf_config, tpu_env
from tf_operator_tpu.core import controller as ctrl
from tf_operator_tpu.core.cluster import (
    InMemoryCluster,
    ObjectMeta,
    Pod,
    PodPhase,
    Service,
    ServicePort,
)
from tf_operator_tpu.core import status_writer as status_writer_lib
from tf_operator_tpu.gang import elastic as elastic_lib
from tf_operator_tpu.gang import podgroup as gang
from tf_operator_tpu.status import engine as status_engine
from tf_operator_tpu.status import metrics
from tf_operator_tpu.telemetry import journal as journal_lib
from tf_operator_tpu.utils import naming
from tf_operator_tpu.utils.env import getenv_int
from tf_operator_tpu.utils.exit_codes import (
    EXIT_USER_RETRYABLE,
    is_retryable_exit_code,
    is_signal_exit,
)
from tf_operator_tpu.utils.logging import logger_for_key

# Fork TTL defaults (ref job.go:25-26,183-202): a finished job with no
# explicit TTL is GC'd after 15min ONLY when cleanPodPolicy==All and the job
# did not fail; anything else keeps 7 days for debugging.
ENV_TTL_CLEAN = "ttlSecondsAfterFinished"
ENV_TTL_DEBUG = "ttlSecondsAfterFinishedDebug"
DEFAULT_TTL_CLEAN_S = 15 * 60
DEFAULT_TTL_DEBUG_S = 7 * 24 * 3600

# Legacy slice-claim annotation key. The controller no longer writes it —
# the claim record lives in status.slice_ids (see _record_slices) so it
# rides the one /status patch per sync instead of costing every job a
# second main-resource write. Jobs persisted by older operators may still
# carry the annotation; it is simply left alone (the allocator re-derives
# claims on sync, so nothing reads it back).
ANNOTATION_SLICE = "tpujob.dev/slice"

SLICE_RETRY_DELAY_S = 15.0

# Progress proxy for deployments with no heartbeat signal (no shared
# log volume): a gang generation that stayed up this long before failing
# was working, so its failure is a fresh incident, not the next lap of a
# crash-loop — the consecutive-restart tally resets. Rapid crash-loops
# (startup import errors, bad checkpoints) die far inside this window
# and still exhaust backoffLimit. A deterministic mid-training failure
# that takes longer than this each lap is indistinguishable from
# occasional preemptions without step data — the fallback favors keeping
# long-running jobs alive; wire a heartbeat for exact semantics.
GANG_PROGRESS_FALLBACK_RUNTIME_S = 600.0


class TrainJobController(ctrl.JobControllerBase):
    def __init__(
        self,
        cluster: InMemoryCluster,
        enable_gang: bool = True,
        gang_scheduler_name: str = gang.DEFAULT_GANG_SCHEDULER,
        slice_allocator: gang.SliceAllocator | None = None,
        keep_failed_pods: bool = True,
        heartbeat_source=None,
        scheduler=None,
        queue_shards: int = 1,
        fleet_policy=None,
        enqueue_router=None,
        status_coalesce_window: float = 0.0,
    ):
        super().__init__(cluster, queue_shards=queue_shards,
                         enqueue_router=enqueue_router)
        self.enable_gang = enable_gang
        self.gang_scheduler_name = gang_scheduler_name
        # Fleet scheduler (sched.FleetScheduler): priority/quota/fair-share
        # admission + graceful preemption above the gang layer. When set,
        # it OWNS the slice allocator — `_admit_slice` consults decide()
        # instead of the allocator directly, and validation enforces its
        # FleetPolicy (unknown priorityClass fails the job, not silently
        # default-priority).
        self.scheduler = scheduler
        if scheduler is not None and slice_allocator is None:
            slice_allocator = scheduler.allocator
        self.slice_allocator = slice_allocator
        # Fleet policy for VALIDATION (unknown priorityClass, zero-quota
        # namespace) — also honored with no scheduler/slices configured,
        # so a --fleet-config-only deployment still rejects typo'd
        # classes instead of silently running them at default priority.
        self.fleet_policy = fleet_policy or (
            scheduler.policy if scheduler is not None else None)
        self.keep_failed_pods = keep_failed_pods
        # Deterministic preemption e2es: `preempt:step=N,job=NAME`
        # directives in TPUJOB_CHAOS make THIS controller evict the named
        # job once its heartbeat crosses step N — the same graceful
        # eviction path a real higher-priority arrival triggers, minus the
        # nondeterministic arrival timing. One-shot markers share
        # TPUJOB_CHAOS_STATE with the trainer-side directives.
        from tf_operator_tpu import chaos as chaos_lib

        self._chaos_preempts = chaos_lib.preempt_directives()
        self._chaos_state = chaos_lib.OneShotState.from_env()
        self._chaos_preempt_warned: set[str] = set()
        # Degraded-capacity e2es: `capacity:slices=N` directives dial the
        # slice inventory (gang.SliceAllocator.set_capacity) without real
        # node loss. Step-less directives apply at startup; at_step ones
        # poll the named job's heartbeat like `preempt:` (one-shot).
        self._chaos_capacity = chaos_lib.capacity_directives()
        self._chaos_capacity_warned: set[str] = set()
        for d in self._chaos_capacity:
            if "at_step" not in d.params:
                # A step-less dial describes inventory STATE, not an
                # event: re-apply on EVERY operator start (the allocator
                # is rebuilt in memory, and a failover silently restoring
                # capacity the scenario models as lost would scale
                # reshaped gangs back up onto nothing). Only at_step
                # dials are one-shot.
                self._apply_capacity(d)
        # Anything with `job_heartbeat(ns, name) -> {"step", "t", ...} | None`
        # (telemetry.collector.TelemetryCollector). Drives the hang watchdog
        # and the consecutive-restart reset; None disables both (the
        # EXIT-CODE half of gang recovery still works — it needs only pod
        # phases).
        self.heartbeat_source = heartbeat_source
        self._now = time.time  # injectable clock for TTL/deadline tests
        # Stuck-Pending warnings already emitted, as "{job key}:{pod uid}"
        # (dedup: one Warning per pod, re-armed only by pod replacement or
        # operator restart — level-triggered reconcile would otherwise spam
        # one event per sync). Job-scoped keys let each sync AND the
        # job-deletion hook purge their own entries, so pod/job churn
        # can't grow the set without bound.
        self._stuck_pending_warned: set[str] = set()
        # The counted-but-not-yet-drained gang-roll latch lives in
        # status.pending_gang_roll_uids (persisted, not here): an operator
        # failover between the count and the drain must re-issue the
        # deletes WITHOUT re-counting the same incident.
        # Round 17: every status/annotation persist goes through ONE
        # coalescing writer — a no-op sync writes nothing, a dirty sync
        # writes one diffed merge-patch, and (opt-in, window > 0) a
        # fast job's queued/admitted/running transitions merge into its
        # terminal write. Fenced with the observed resourceVersion when
        # the substrate serves possibly-stale lister-snapshot reads.
        # Coalescing contract (status_writer.py): a deferred flush
        # writes nothing and keeps no diff — every non-urgent status
        # mutation this controller makes must be recomputable from a
        # fresh observation (all of sync_job's are: conditions, replica
        # tallies, and bookkeeping derive from the job+pods it reads
        # each pass); anything transient-derived must flush urgent.
        self._status_writer = status_writer_lib.StatusWriter(
            cluster.update_job_status, kind=TrainJob.KIND,
            window=status_coalesce_window, clock=lambda: self._now(),
            defer=lambda key, delay: self.queue.add_after(key, delay),
            # Default False: only substrates that declare they may serve
            # stale lister reads get fenced — read-through substrates
            # (InMemoryCluster) skip it so the merge-patch lane stays
            # conflict-free against concurrent spec editors.
            fence=bool(getattr(cluster, "lists_from_cache", False)),
        )
        self.cluster.on_add("TrainJob", self._count_created)
        self.cluster.on_delete("TrainJob", self._count_deleted)
        self.cluster.on_delete("TrainJob", self._purge_job_state)

    @staticmethod
    def _count_created(job: TrainJob) -> None:
        # Labeled child series (round 8): per-namespace breakdowns are the
        # difference between "a job failed somewhere" and "team X's
        # namespace is failing" on one dashboard.
        metrics.jobs_created.labels(namespace=job.namespace).inc()
        # Flight recorder: the submit event anchors every later phase
        # duration (time-to-admission, -running, -first-step). Nameless
        # stubs (metrics tests exercise the counter alone) skip it.
        name = getattr(job, "name", None)
        if name:
            journal_lib.get_journal().record(
                f"{job.namespace}/{name}", "submit")

    @staticmethod
    def _count_deleted(job: TrainJob) -> None:
        metrics.jobs_deleted.labels(namespace=job.namespace).inc()

    # ------------------------------------------------------------------ sync

    def sync_job(self, key: str) -> None:
        """One reconcile pass for one job (syncTFJob, controller.go:286)."""
        metrics.reconcile_total.inc()
        ns, name = naming.split_job_key(key)
        shared = self.cluster.try_get_job(ns, name)
        if (shared is not None
                and getattr(self.cluster, "lists_from_cache", False)
                and (shared.status.pending_preemption_uids
                     or shared.status.pending_gang_roll_uids)):
            # A destructive drain latch replays pod deletes and
            # scheduler requeues in THIS sync — that needs
            # read-your-writes, which a lister-cache observation cannot
            # promise: the flush-time rv fence converts a stale WRITE
            # into a requeue but cannot undo deletes already issued
            # from a stale latch. One read-through GET re-verifies the
            # latch before anything acts on it (round-17 review).
            shared = self.cluster.try_get_job(ns, name, read_through=True)
        if shared is None:
            # Deleted between enqueue and sync: drop bookkeeping.
            for rtype in ReplicaType:
                self.expectations.delete_expectations(
                    naming.gen_expectation_pods_key(key, str(rtype))
                )
                self.expectations.delete_expectations(
                    naming.gen_expectation_services_key(key, str(rtype))
                )
            self._release_capacity(key)
            self._status_writer.forget(key)
            return

        job = shared.deep_copy()
        api_defaults.set_defaults(job)
        # The coalescing writer's dirty/diff baseline: the observed state
        # this sync started from (post-defaults — defaults never touch
        # status or annotations, so the wire form matches the store).
        base = job.deep_copy()

        # Invalid spec: mark Failed, emit event, never crash (parity with the
        # unstructured-informer tolerance + invalid_tfjob_tests behavior).
        # With a fleet scheduler, its policy joins the invariants (unknown
        # priorityClass, zero-quota namespace) — enforced BEFORE admission.
        problems = api_validation.validate_job(job, fleet=self.fleet_policy)
        if problems:
            msg = "; ".join(problems)
            self.cluster.record_event(
                TrainJob.KIND, ns, name, "Warning",
                status_engine.REASON_INVALID_SPEC, msg,
            )
            changed = status_engine.set_condition(
                job.status, JobConditionType.FAILED,
                status_engine.REASON_INVALID_SPEC, msg, self._now(),
            )
            if job.status.completion_time is None:
                job.status.completion_time = self._now()
                changed = True
            journal_lib.get_journal().record(
                key, "validate", ok=False, problems=len(problems),
                msg=msg[:200])
            if changed:
                metrics.jobs_failed.labels(namespace=job.namespace).inc()
                self._flush(job, base, urgent=True)
            return

        if not self._expectations_satisfied(key, job):
            return

        self.reconcile(job, base)

    def _expectations_satisfied(self, key: str, job: TrainJob) -> bool:
        """satisfiedExpectations (controller.go:477-496)."""
        for rtype in job.spec.replica_specs:
            if not self.expectations.satisfied(
                naming.gen_expectation_pods_key(key, str(rtype))
            ):
                return False
            if not self.expectations.satisfied(
                naming.gen_expectation_services_key(key, str(rtype))
            ):
                return False
        return True

    # ----------------------------------------------------- status persisting

    def _flush(self, job: TrainJob, base: TrainJob, *,
               urgent: bool = False):
        """StatusWriter front-end all persist paths go through: journals
        this sync's condition TRANSITIONS (and derives the scheduling/
        recovery phase histograms from them) before handing the write to
        the coalescing writer — one chokepoint, so no flush site can
        change a condition without the flight recorder seeing it."""
        self._journal_conditions(job, base)
        return self._status_writer.flush(job, base, urgent=urgent)

    def _journal_conditions(self, job: TrainJob, base: TrainJob) -> None:
        """Record each condition whose (status, reason) changed this sync.
        Running newly-true additionally samples the phase histograms —
        BEFORE the new events land, so last_ts still sees the previous
        Running/roll marks."""
        if job.status.conditions == base.status.conditions:
            return
        jrnl = journal_lib.get_journal()
        if not jrnl.enabled:
            return
        key = job.key()
        prev = {str(c.type): (bool(c.status), c.reason)
                for c in base.status.conditions}
        for c in job.status.conditions:
            cur = (bool(c.status), c.reason)
            ctype = str(c.type)
            if prev.get(ctype) == cur:
                continue
            if ctype == str(JobConditionType.RUNNING) and cur[0]:
                self._observe_running_phases(jrnl, key)
            jrnl.record(key, "condition", type=ctype, status=cur[0],
                        reason=c.reason)

    @staticmethod
    def _observe_running_phases(jrnl, key: str) -> None:
        """Running just asserted: one phase sample. After a gang roll or
        preemption latch newer than the previous Running mark this is the
        RECOVERY duration (restart-to-recovery MTTR); on the FIRST
        Running it is the SCHEDULING duration (slice admitted -> gang
        actually running, i.e. pod startup under the operator's control
        — trainer-side startup is the collector's `startup` phase)."""
        now_ns = time.perf_counter_ns()
        t_prev_run = jrnl.last_ts(key, "condition",
                                  type=str(JobConditionType.RUNNING),
                                  status=True)
        rolls = [t for t in (jrnl.last_ts(key, "gang.roll"),
                             jrnl.last_ts(key, "preempt.latch"))
                 if t is not None]
        t_roll = max(rolls) if rolls else None
        if t_roll is not None and (t_prev_run is None or t_roll > t_prev_run):
            metrics.job_phase_seconds.labels(phase="recovery").observe(
                max(0.0, (now_ns - t_roll) / 1e9))
        elif t_prev_run is None:
            t0 = jrnl.last_ts(key, "slice.admit") or jrnl.first_ts(key)
            if t0 is not None:
                metrics.job_phase_seconds.labels(phase="scheduling").observe(
                    max(0.0, (now_ns - t0) / 1e9))

    # ------------------------------------------------------------- reconcile

    def reconcile(self, job: TrainJob, base: TrainJob | None = None) -> None:
        """reconcileTFJobs (controller.go:332). `base` is the pristine
        observed copy the status writer diffs flushes against; direct
        callers (tests) may omit it."""
        key = job.key()
        if base is None:
            base = job.deep_copy()

        status_engine.set_condition(
            job.status, JobConditionType.CREATED, status_engine.REASON_CREATED,
            f"TrainJob {key} is created.", self._now(),
        )

        pods = self.get_pods_for_job(job)
        services = self.get_services_for_job(job)

        # Suspend (beyond the reference; batch/v1 Job.spec.suspend shape):
        # tear down every pod AND the gang/slice claim but keep the job;
        # flipping suspend back resumes via the normal reconcile (trainers
        # continue from checkpoints). Terminal states win over suspend.
        if job.spec.run_policy.suspend and not is_terminal(job.status):
            if pods:
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Normal",
                    "Suspended", f"Suspending: deleting {len(pods)} pod(s)",
                )
            for pod in pods:
                self._tracked_delete_pod(job, pod)
            for svc in services:
                self._tracked_delete_service(job, svc)
            if self.enable_gang:
                gang.delete_podgroup(self.cluster, job)
            self._release_capacity(key)
            status_engine.set_condition(
                job.status, JobConditionType.SUSPENDED,
                status_engine.REASON_SUSPENDED,
                f"TrainJob {key} is suspended.", self._now(),
            )
            self._flush(job, base)
            return

        exceeded, exceed_reason, exceed_msg = self._past_limits(job, pods)

        if is_terminal(job.status) or exceeded:
            if exceeded and not is_terminal(job.status):
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Warning",
                    exceed_reason, exceed_msg,
                )
                status_engine.set_condition(
                    job.status, JobConditionType.FAILED, exceed_reason,
                    exceed_msg, self._now(),
                )
                if job.status.completion_time is None:
                    job.status.completion_time = self._now()
                metrics.jobs_failed.labels(namespace=job.namespace).inc()
            self._delete_pods_and_services(job, pods, services)
            if self.enable_gang:
                gang.delete_podgroup(self.cluster, job)
            self._release_capacity(job.key())
            # Status must be durable before TTL GC may delete the job:
            # urgent — terminal conditions never sit in the window.
            self._flush(job, base, urgent=True)
            self._cleanup_by_ttl(job)
            return

        # Gang: PodGroup + atomic slice admission gate pod creation. With
        # a fleet scheduler, the PodGroup syncs only once ADMITTED — the
        # scheduler replaces kube-batch as the arbiter, so a queued job's
        # every retry paying a PodGroup GET would be pure apiserver load
        # at fleet scale (the group object exists for external gang
        # schedulers to observe, which only matters once pods exist).
        # Chaos capacity directives targeting this job's heartbeat fire
        # BEFORE admission, so the shrunk inventory is what admission sees.
        self._capacity_tick(job, key)

        if self.enable_gang and job.spec.run_policy.scheduling.gang:
            pre_synced = False
            if (self.scheduler is None
                    and job.status.reshaped_replicas is None):
                # Reshaped jobs sync their PodGroup AFTER the reshape
                # fold below — syncing here too would flip minMember
                # full/degraded/full every pass.
                gang.sync_podgroup(self.cluster, job)
                pre_synced = True
            retry_delay = self._admit_slice(job, key, pods)
            if retry_delay is not None:
                self._flush(job, base)
                self.queue.add_after(key, retry_delay)
                return
            # Elastic reshape: while status says the gang runs degraded,
            # reconcile toward the REDUCED size — the working copy's
            # worker count, mesh data axis, and slice topology all shrink
            # together, so pod env (TF_CONFIG/TPUJOB_MESH/JAX world) and
            # the topology hash stay mutually consistent and the existing
            # elastic roll machinery does the resizing.
            self._apply_reshape(job)
            if self.scheduler is not None or not pre_synced:
                # AFTER the reshape fold, so a degraded gang's PodGroup
                # carries the REDUCED minMember (an external gang
                # scheduler observing the group must not wait for a full
                # count that will never come). `not pre_synced` also
                # covers the pass that CLEARS a reshape: minMember must
                # go back to full in the same sync the roll-up starts.
                gang.sync_podgroup(self.cluster, job)
            if job.status.reshaped_replicas is not None:
                # Degraded gangs keep probing for their full size (kicks
                # from releases are the fast path; this is the net).
                self.queue.add_after(key, SLICE_RETRY_DELAY_S)

        metrics.gang_size.labels(
            namespace=job.namespace, job=job.name
        ).set(sum(
            int(s.replicas or 0)
            for rt, s in job.spec.replica_specs.items()
            if tpu_env.is_spmd_replica(rt)
        ))

        # Graceful preemption (fleet scheduler eviction or chaos
        # `preempt:` directive): evict, drain, requeue — skipping the
        # per-type loop, exactly like a gang roll (deletions drive the
        # next sync). Runs BEFORE gang recovery so an eviction in flight
        # can never be double-counted as a retryable failure.
        doomed = self._preemption_tick(job, pods, key)
        if doomed is not None:
            if job.status != base.status:
                job.status.last_reconcile_time = self._now()
            # Urgent, and flushed BEFORE the deletes it authorizes: the
            # pending_preemption_uids drain latch must be durable — and,
            # when fenced, proven fresh (a stale lister observation 409s
            # here into a requeue) — ahead of any destructive side
            # effect this sync takes from it.
            self._flush(job, base, urgent=True)
            self._delete_gang_pods(job, key, doomed)
            return

        # Pods/services of replica types REMOVED from the spec would never be
        # visited by the per-type loop: delete them, or their stale topology
        # label holds the two-phase roll gate forever (wedging creations).
        known = {str(rt).lower() for rt in job.spec.replica_specs}
        for pod in pods:
            rt = pod.metadata.labels.get(ctrl.LABEL_REPLICA_TYPE, "")
            if rt and rt not in known and not pod.is_finished():
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Normal",
                    "ScaleDown",
                    f"Deleting pod {pod.name}: replica type {rt!r} removed "
                    f"from spec",
                )
                self._tracked_delete_pod(job, pod)
        for svc in services:
            rt = svc.metadata.labels.get(ctrl.LABEL_REPLICA_TYPE, "")
            if rt and rt not in known:
                self._tracked_delete_service(job, svc)

        # Stuck-Pending detection (recovery.pendingTimeoutSeconds): a pod
        # wedged in Pending — unschedulable slice, image pull failure —
        # gets a Warning event and lands in status.stuck_pending_pods
        # instead of the job sitting silently in Created forever.
        self._check_stuck_pending(job, pods, key)

        # Gang-coherent recovery (recovery.policy=gang): a retryable
        # gang-member failure (or a heartbeat-stale hang) rolls the WHOLE
        # gang instead of one pod. When this sync initiated (or
        # backoff-failed) a gang restart, the per-type loop is skipped —
        # the deletions' events drive the next sync, which recreates the
        # gang through the normal creation path once the old generation is
        # fully drained (same two-phase discipline as the elastic roll).
        doomed = self._gang_recovery_tick(job, pods, key)
        if doomed is not None:
            if job.status != base.status:
                job.status.last_reconcile_time = self._now()
            # Urgent, and flushed BEFORE the deletes it authorizes:
            # pending_gang_roll_uids is the don't-double-count latch an
            # operator failover replays deletes from — it must be
            # durable (and, when fenced, proven fresh) before any pod
            # dies for it.
            self._flush(job, base, urgent=True)
            self._delete_gang_pods(job, key, doomed)
            return

        for rtype, spec in sorted(
            job.spec.replica_specs.items(), key=lambda kv: str(kv[0])
        ):
            self.reconcile_pods(job, pods, rtype, spec)
            self.reconcile_services(job, services, rtype, spec)

        # Schedule a wake-up at the active deadline so expiry is noticed even
        # with no pod events (ref job.go:136-152).
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is not None and job.status.start_time is not None:
            remaining = job.status.start_time + deadline - self._now()
            if remaining > 0:
                self.queue.add_after(key, remaining + 0.1)

        if job.status != base.status:
            job.status.last_reconcile_time = self._now()
        # Urgent when this sync TRANSITIONED the job to terminal (the
        # terminal branch above only handles already-terminal observations;
        # letting the first Succeeded/Failed write sit in the window would
        # stall teardown+TTL — and the whole fleet pipeline — one window
        # per job) or recorded a reshape (a durability latch: the degraded
        # size must survive an operator failover).
        self._flush(
            job, base,
            urgent=(is_terminal(job.status) and not is_terminal(base.status))
            or job.status.reshaped_replicas != base.status.reshaped_replicas,
        )

    @staticmethod
    def _elastic_enabled(job: TrainJob) -> bool:
        rec = job.spec.run_policy.recovery
        return (rec.policy == "gang" and rec.elastic.reshape_on_recovery
                and job.spec.tpu is not None
                and bool(job.spec.tpu.topology))

    def _degraded_candidates(self, job: TrainJob):
        """(topology, scaled worker count) for every free smaller slice
        class the gang can cleanly shrink onto, largest first."""
        workers = job.spec.replica_specs.get(ReplicaType.WORKER)
        full_workers = int(workers.replicas or 0) if workers else 0
        if full_workers < 1 or self.slice_allocator is None:
            return
        minr = job.spec.run_policy.recovery.elastic.min_replicas or 1
        axes = job.spec.mesh.axes if job.spec.mesh else None
        full = job.spec.tpu.topology
        for cand in self.slice_allocator.free_classes_below(full):
            plan = elastic_lib.degraded_plan(
                full, full_workers, cand, axes, minr
            )
            if plan is not None:
                yield cand, plan[0]

    def _record_reshape(self, job: TrainJob, key: str, scaled: int,
                        topology: str) -> None:
        """Persist a degraded admission: effective size + slice class,
        GangReshaped condition/event, and one reshard-transition sample.
        NEVER touches the restart tallies — a reshape is a placement
        decision, not a failure."""
        workers = job.spec.replica_specs.get(ReplicaType.WORKER)
        prev = job.status.reshaped_replicas
        if prev is None:
            prev = int(workers.replicas or 0) if workers else scaled
        if (job.status.reshaped_replicas == scaled
                and job.status.reshaped_topology == topology):
            return
        job.status.reshaped_replicas = scaled
        job.status.reshaped_topology = topology
        now = self._now()
        direction = "shrink" if scaled < prev else "grow"
        metrics.restore_reshard_total.labels(
            namespace=job.namespace, direction=direction).inc()
        msg = (f"TrainJob {key} re-admitted at {scaled} Worker replica(s) "
               f"on a {topology} slice (spec size unavailable); will "
               f"scale back up when capacity frees.")
        self.cluster.record_event(
            TrainJob.KIND, job.namespace, job.name, "Normal",
            status_engine.REASON_GANG_RESHAPED,
            f"Gang reshaped {prev} -> {scaled} Worker replica(s) onto "
            f"{topology}; trainers resume from the shared checkpoint via "
            f"reshard-on-restore",
        )
        status_engine.set_condition(
            job.status, JobConditionType.GANG_RESHAPED,
            status_engine.REASON_GANG_RESHAPED, msg, now,
        )
        journal_lib.get_journal().record(
            key, "reshape", direction=direction, scaled=scaled,
            topology=topology)

    def _record_slices(self, job: TrainJob, slice_ids: list[str]) -> None:
        """Record the slice claim in status.slice_ids (idempotent). The
        allocator/scheduler stays authoritative; this is the durable
        observability record, kept in STATUS so it ships inside the same
        /status patch as the conditions — an annotation here would cost
        every admitted job a second main-resource write per sync wave."""
        ids = [s for s in slice_ids if s]
        if job.status.slice_ids == ids:
            return
        was_empty = not job.status.slice_ids
        job.status.slice_ids = ids
        if self.scheduler is None and ids and was_empty:
            # Scheduler-less deployments: the allocator grant IS the
            # admission (with a FleetScheduler, _admit_locked records
            # slice.admit and the admission phase itself).
            jrnl = journal_lib.get_journal()
            key = job.key()
            jrnl.record(key, "slice.admit", slice=",".join(ids))
            t0 = jrnl.first_ts(key)
            if t0 is not None:
                metrics.job_phase_seconds.labels(phase="admission").observe(
                    max(0.0, (time.perf_counter_ns() - t0) / 1e9))

    def _record_full_size(self, job: TrainJob, key: str) -> bool:
        """Full-size (re)admission: clear any reshape state, lower the
        GangReshaped condition, count the grow transition. True when a
        reshape was actually cleared (the upgrade freed a smaller slice
        someone else may want)."""
        if job.status.reshaped_replicas is None:
            return False
        prev = job.status.reshaped_replicas
        job.status.reshaped_replicas = None
        job.status.reshaped_topology = ""
        now = self._now()
        metrics.restore_reshard_total.labels(
            namespace=job.namespace, direction="grow").inc()
        self.cluster.record_event(
            TrainJob.KIND, job.namespace, job.name, "Normal",
            status_engine.REASON_GANG_RESTORED,
            f"Capacity returned: gang scaling back up from {prev} to its "
            f"spec size; trainers resume from the newest checkpoint",
        )
        status_engine.lower_condition(
            job.status, JobConditionType.GANG_RESHAPED,
            status_engine.REASON_GANG_RESTORED,
            f"TrainJob {key} is back at its spec size.", now,
        )
        journal_lib.get_journal().record(
            key, "reshape", direction="restore", prev=prev)
        return True

    def _apply_reshape(self, job: TrainJob) -> None:
        """Fold status.reshaped_replicas into the WORKING COPY of the
        spec (never the stored object): worker count, mesh data axis, and
        slice topology shrink together so everything derived downstream
        (TF_CONFIG, TPUJOB_MESH, topology hash, TPU resources, podgroup
        minMember) reflects the degraded gang."""
        n = job.status.reshaped_replicas
        if n is None:
            return
        spec = job.spec.replica_specs.get(ReplicaType.WORKER)
        if spec is None:
            return
        full = int(spec.replicas or 0)
        if full <= 0 or n >= full:
            return
        if job.spec.mesh is not None and job.spec.mesh.axes:
            scaled_axes = elastic_lib.scaled_mesh_axes(
                job.spec.mesh.axes, full, n
            )
            if scaled_axes is None:
                return  # unreachable: admission only reshapes with a plan
            job.spec.mesh.axes = scaled_axes
        spec.replicas = n
        if job.status.reshaped_topology and job.spec.tpu is not None:
            job.spec.tpu.topology = job.status.reshaped_topology

    def _admit_slice(self, job: TrainJob, key: str,
                     pods: list[Pod] | None = None) -> float | None:
        """Whole-slice admission: None when pods may be created, else the
        retry delay before this job should re-check.

        With a fleet scheduler the decision adds priority/fair-share
        ordering, namespace quota, and preemption on the job's behalf; a
        deferred job gets a Queued condition and its position is served
        live by the API. Releases wake the exact jobs the freed capacity
        serves (kick_targets), so the timer is only a safety net — and it
        scales with queue position: a job 500-deep re-checking every 15 s
        is pure apiserver load, it cannot possibly admit before hundreds
        of releases each of which would have kicked it. Without a
        scheduler, this is the original first-come allocator gate.

        Elastic recovery (recovery.elastic.reshapeOnRecovery) adds the
        degraded path: when the full-size class has no capacity, admit
        onto the largest free SMALLER class the gang can cleanly shrink
        to (>= minReplicas) instead of pinning Pending — and, every sync
        while degraded, try to upgrade back to full size."""
        if job.spec.tpu is None or not job.spec.tpu.topology:
            return None
        full_topology = job.spec.tpu.topology
        elastic = self._elastic_enabled(job)
        live = any(not p.is_finished() for p in (pods or []))

        # A claim on a slice that went offline (capacity lost, chaos
        # `capacity:` shrink) survives while the gang's pods do — real
        # slice loss kills them anyway — and is dropped once the gang has
        # drained, so re-admission runs fresh (degraded, when elastic).
        if (self.slice_allocator is not None and not live
                and self.slice_allocator.held_offline(key)):
            self.cluster.record_event(
                TrainJob.KIND, job.namespace, job.name, "Warning",
                "SliceLost",
                f"slice {','.join(job.status.slice_ids) or None} "
                f"went offline while held; releasing the claim for "
                f"re-admission",
            )
            if self.scheduler is not None:
                # requeue_preempted, not release: a capacity-loss victim
                # keeps its ORIGINAL submit time exactly like a
                # preemption victim — losing a slice must not also cost
                # the gang its FIFO standing among peers.
                self.scheduler.requeue_preempted(job)
            else:
                self.slice_allocator.release(key)

        # Scale-up drain cleanup: a gang that claimed its full-size slice
        # while the degraded generation was still live holds BOTH (so no
        # waiter lands on chips the old pods occupy). Once no live pod of
        # the old generation remains, free the degraded slice and wake
        # the waiters it can serve — the same drain-before-release
        # discipline as preemption.
        if (self.slice_allocator is not None
                and job.status.reshaped_replicas is None
                and tpu_env.num_slices(job) == 1
                and len(self.slice_allocator.held_slices(key)) > 1):
            cur_hash = tf_config.topology_hash(job)
            stale_live = any(
                p.metadata.labels.get(ctrl.LABEL_SPEC_HASH)
                not in (None, cur_hash) and not p.is_finished()
                for p in (pods or [])
            )
            if not stale_live and self.slice_allocator.release_except_class(
                    key, full_topology):
                self._kick_slice_waiters()

        if self.scheduler is None:
            return self._admit_slice_allocator(
                job, key, full_topology, elastic
            )

        decision = self.scheduler.decide(job)
        if not decision.admit and elastic and decision.reason == "capacity":
            # Same degraded path for fleet deployments: a capacity-blocked
            # elastic job (fresh, gang-rolled, or preempted-and-requeued)
            # takes whatever smaller class the scheduler will grant —
            # ranked waiters keep their reservations, so this never
            # steals a slice a higher-priority job was promised.
            for cand, scaled in self._degraded_candidates(job):
                d2 = self.scheduler.decide(job, topology=cand)
                if d2.admit:
                    self._record_reshape(job, key, scaled, cand)
                    decision = d2
                    break
        if decision.admit:
            running_cls = self.scheduler.running_class(key)
            if (running_cls is not None
                    and running_cls == gang.slice_class(full_topology)):
                # Note: the degraded slice is NOT freed yet — the gang
                # holds both until the old generation drains; the
                # cleanup block above releases it and kicks the waiters.
                self._record_full_size(job, key)
            if decision.slice_id:
                self._record_slices(job, [decision.slice_id])
            return None
        sched = job.spec.run_policy.scheduling
        if decision.reason == "quota":
            reason, msg = status_engine.REASON_QUOTA, (
                f"namespace {job.namespace} ResourceQuota exhausted; "
                f"queued in {sched.queue or 'default'}"
            )
        else:
            reason, msg = status_engine.REASON_QUEUED, (
                f"no free {job.spec.tpu.topology} slice; queued in "
                f"{sched.queue or 'default'}"
                + (" (preempting a lower-priority job)"
                   if decision.preempting else "")
            )
        # A freshly-preempted victim keeps its Preempted condition as the
        # activity state while it waits — Queued would overwrite the one
        # visible record that the disruption was planned, not a failure.
        # The event fires only on a condition CHANGE: waiters re-decide on
        # every kick/retry, and one event per re-check would flood the
        # event log at fleet scale.
        if not has_condition(job.status, JobConditionType.PREEMPTED):
            if status_engine.set_condition(
                job.status, JobConditionType.QUEUED, reason, msg, self._now(),
            ):
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Normal",
                    "Queued", f"{msg} (position {decision.position})",
                )
        if decision.preempting:
            # Run the victims' evictions promptly (each one's own sync
            # executes it through the graceful SIGTERM -> emergency-
            # checkpoint path); a victim may be a serve replica — route
            # by key shape. k-victim preemption can mark several.
            for victim in (decision.victims or (decision.preempting,)):
                self.route_enqueue(victim)
        return SLICE_RETRY_DELAY_S + min(
            120.0, 0.25 * (decision.position or 0))

    def _admit_slice_allocator(self, job: TrainJob, key: str,
                               full_topology: str,
                               elastic: bool) -> float | None:
        """The scheduler-less admission gate (first-come allocator), with
        the elastic upgrade/degrade paths folded in."""
        if self.slice_allocator is None:
            return None
        n = tpu_env.num_slices(job)
        if n > 1:
            # Multi-slice: all N slices or NOTHING (admit_many never takes
            # a partial hold — a 2-slice job sitting on 1 of 3 slices
            # would deadlock against another doing the same while 1-slice
            # waiters starve behind capacity nobody can use). Idempotent
            # per holder; elastic reshape is excluded by validation.
            sids = self.slice_allocator.admit_many(key, full_topology, n)
            if sids is not None:
                self._record_slices(job, sids)
                return None
            free = self.slice_allocator.free_of_class(full_topology)
            self.cluster.record_event(
                TrainJob.KIND, job.namespace, job.name, "Warning",
                "SliceUnavailable",
                f"need {n} free {full_topology} slices admitted atomically "
                f"({free} free; holding none — no partial claim); "
                f"gang-waiting",
            )
            return SLICE_RETRY_DELAY_S
        # A FULL-SIZE claim stands wherever it is — online, or offline
        # under a still-live gang (the drained-offline case released it
        # above). Never shopping for a different slice here is what keeps
        # a live gang from being silently migrated onto a same-class
        # slice its pods don't occupy; only RESHAPED gangs change class.
        # (A scale-up briefly holds two slices: the full-class one is the
        # authoritative annotation while the degraded one drains.)
        if (self.slice_allocator.holding(key) is not None
                and job.status.reshaped_replicas is None):
            held = (self.slice_allocator.holding_class(key, full_topology)
                    or self.slice_allocator.holding(key))
            self._record_slices(job, [held])
            return None
        # Full size first — `claim` is both the fresh admission and the
        # scale-back-up: a reshaped gang with live pods keeps its
        # degraded slice held (hold-both) until the drain cleanup in
        # _admit_slice frees it.
        slice_id = self.slice_allocator.claim(key, full_topology)
        if slice_id is not None:
            self._record_full_size(job, key)
            self._record_slices(job, [slice_id])
            return None
        # Full size unavailable. A reshaped gang's degraded claim stands
        # (admit is idempotent by holder).
        held = self.slice_allocator.admit(key, full_topology)
        if held is not None:
            self._record_slices(job, [held])
            return None
        if elastic:
            for cand, scaled in self._degraded_candidates(job):
                sid = self.slice_allocator.upgrade(key, cand)
                if sid is None:
                    continue  # raced: try the next class
                self._record_reshape(job, key, scaled, cand)
                self._record_slices(job, [sid])
                return None
        self.cluster.record_event(
            TrainJob.KIND, job.namespace, job.name, "Warning",
            "SliceUnavailable",
            f"no free {full_topology} slice"
            + (" (and no reshapeable smaller class)" if elastic else "")
            + "; gang-waiting",
        )
        return SLICE_RETRY_DELAY_S

    # ------------------------------------------------- gang-coherent recovery

    @staticmethod
    def _gang_members(pods: list[Pod]) -> list[Pod]:
        """Pods participating in the collective: everything except
        Evaluators (they follow the checkpoint stream from OUTSIDE the
        SPMD world — cluster_spec never enrolls them — so a gang roll
        neither needs nor wants to kill them)."""
        return [
            p for p in pods
            if p.metadata.labels.get(ctrl.LABEL_REPLICA_TYPE, "")
            != str(ReplicaType.EVALUATOR).lower()
        ]

    @staticmethod
    def _pod_slice(job: TrainJob, pod: Pod) -> int | None:
        """Which slice gang a pod belongs to (multi-slice jobs): the
        slice-id label stamped at creation, else derived from the replica
        index (pre-label pods after an operator upgrade)."""
        v = pod.metadata.labels.get(ctrl.LABEL_SLICE_ID)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                return None
        rt = api_defaults.canonical_replica_type(
            pod.metadata.labels.get(ctrl.LABEL_REPLICA_TYPE, "")
        )
        if rt is None:
            return None
        try:
            idx = int(pod.metadata.labels.get(ctrl.LABEL_REPLICA_INDEX, ""))
        except ValueError:
            return None
        pid = tpu_env.process_id(job, rt, idx)
        return tpu_env.slice_of_process(job, pid) if pid is not None else None

    def _job_heartbeat(self, job: TrainJob) -> dict | None:
        if self.heartbeat_source is None:
            return None
        try:
            return self.heartbeat_source.job_heartbeat(job.namespace, job.name)
        except Exception:
            return None  # a torn/unreadable heartbeat is "no signal", never a crash

    def _purge_job_state(self, job: TrainJob) -> None:
        """Job deleted: drop its stuck-Pending dedup entries (they would
        otherwise linger for the operator's lifetime) and its gang-size
        gauge series (a deleted job must stop being scraped)."""
        key = f"{job.namespace}/{job.name}"
        self._stuck_pending_warned = {
            e for e in self._stuck_pending_warned
            if not e.startswith(key + ":")
        }
        metrics.gang_size.remove(namespace=job.namespace, job=job.name)
        # The ring survives for retention_s so a post-mortem timeline
        # still reconstructs the deleted job.
        journal_lib.get_journal().mark_deleted(key)

    def _check_stuck_pending(self, job: TrainJob, pods: list[Pod], key: str) -> None:
        """recovery.pendingTimeoutSeconds: surface pods wedged in Pending
        (Warning event once per pod + status.stuck_pending_pods)."""
        timeout = job.spec.run_policy.recovery.pending_timeout_seconds
        if timeout is None:
            if job.status.stuck_pending_pods:
                job.status.stuck_pending_pods = []
            return
        now = self._now()
        stuck: list[str] = []
        soonest: float | None = None
        pending_uids: set[str] = set()
        for pod in pods:
            if pod.status.phase != PodPhase.PENDING:
                continue
            pending_uids.add(pod.metadata.uid)
            waited = now - pod.metadata.creation_timestamp
            if waited >= timeout:
                stuck.append(pod.name)
                if f"{key}:{pod.metadata.uid}" not in self._stuck_pending_warned:
                    self._stuck_pending_warned.add(f"{key}:{pod.metadata.uid}")
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Warning",
                        status_engine.REASON_STUCK_PENDING,
                        f"Pod {pod.name} has been Pending for {int(waited)}s "
                        f"(pendingTimeoutSeconds={timeout:g}): unschedulable "
                        f"slice, image pull failure, or scheduler outage",
                    )
            else:
                remaining = timeout - waited
                soonest = remaining if soonest is None else min(soonest, remaining)
        if soonest is not None:
            # Wake up when the youngest Pending pod crosses the deadline —
            # stuck detection must not depend on an unrelated pod event.
            self.queue.add_after(key, soonest + 0.25)
        stuck.sort()
        if stuck != job.status.stuck_pending_pods:
            job.status.stuck_pending_pods = stuck
        # Bound the warned set: every entry of THIS job whose pod is no
        # longer Pending — left the phase, replaced, or deleted outright
        # (deleted pods aren't in `pods` at all, so an is-listed check
        # would leak their uids) — frees its entry.
        self._stuck_pending_warned -= {
            e for e in self._stuck_pending_warned
            if e.startswith(f"{key}:")
            and e.split(":", 1)[1] not in pending_uids
        }

    # ----------------------------------------------------------- chaos capacity

    def _apply_capacity(self, d) -> None:
        """Dial the slice inventory to the directive's `slices=N` (the
        deterministic stand-in for node loss/return). Held slices are not
        revoked — holders notice via held_offline at their next roll."""
        if self.slice_allocator is None:
            return
        affected = self.slice_allocator.set_capacity(int(d.params["slices"]))
        # Affected holders re-sync promptly (their claim's availability
        # changed); restored capacity additionally wakes the waiters.
        for holder in affected:
            self.enqueue(holder)
        self._kick_slice_waiters()

    def _capacity_tick(self, job: TrainJob, key: str) -> None:
        """Fire armed `capacity:...,at_step=S,job=NAME` directives once
        the named job's heartbeat crosses S (one-shot; same polling
        discipline as `preempt:`)."""
        for d in self._chaos_capacity:
            if "at_step" not in d.params:
                continue  # applied at construction
            if d.params.get("job") != job.name:
                continue
            if d.params.get("namespace", "default") != job.namespace:
                continue
            if self._chaos_state.fired(d):
                continue
            if self.heartbeat_source is None:
                if key not in self._chaos_capacity_warned:
                    self._chaos_capacity_warned.add(key)
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name,
                        "Warning", "ChaosCapacityUnarmed",
                        "capacity: directive keys on this job's heartbeat "
                        "but the operator has no heartbeat source "
                        "(--log-dir); the step boundary can never be "
                        "observed",
                    )
                continue
            hb = self._job_heartbeat(job)
            step = hb.get("step") if hb else None
            if step is not None and int(step) >= int(d.params["at_step"]):
                self._chaos_state.mark(d)
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Normal",
                    "ChaosCapacity",
                    f"capacity directive fired at step >= "
                    f"{d.params['at_step']}: slice inventory -> "
                    f"{d.params['slices']}",
                )
                self._apply_capacity(d)
            else:
                self.queue.add_after(key, 0.3)

    # ------------------------------------------------------ graceful preemption

    def _chaos_preempt_due(self, job: TrainJob):
        """The unfired `preempt:step=N,job=NAME` chaos directive targeting
        this job, or None. Returns (directive, ready): ready=False means
        the heartbeat has not crossed the step yet (poll again soon)."""
        for d in self._chaos_preempts:
            if d.params.get("job") != job.name:
                continue
            if d.params.get("namespace", "default") != job.namespace:
                continue
            if self._chaos_state.fired(d):
                continue
            hb = self._job_heartbeat(job)
            step = hb.get("step") if hb else None
            if step is not None and int(step) >= int(d.params["step"]):
                return d, True
            return d, False
        return None, False

    def _preemption_tick(self, job: TrainJob, pods: list[Pod],
                         key: str) -> list[Pod] | None:
        """Graceful eviction: triggered by the fleet scheduler (a pending
        higher-priority job claimed this gang's slice) or by a chaos
        `preempt:` directive (deterministic e2es). Dooms every
        non-succeeded pod — the runtime SIGTERMs them, trainers finish the
        in-flight step and emergency-checkpoint (PR 4), the drain
        discipline SIGKILLs stragglers (PR 5) — then requeues the job with
        a Preempted condition. The restart tally is NEVER touched: a
        planned eviction is not a failure, and counting it against
        backoffLimit would fail exactly the long-running low-priority jobs
        preemption targets. Returns None when this sync did not act, else
        the pods to delete (possibly none): the caller skips the per-type
        loop and issues the deletes only AFTER the latch flush succeeds,
        so a stale fenced observation 409s before anything dies."""
        # Drain phase first: a counted preemption re-issues its deletes
        # across syncs (and operator failovers — the latch is in status)
        # without ever re-counting the incident.
        if job.status.pending_preemption_uids:
            pending = set(job.status.pending_preemption_uids)
            left = [p for p in pods if p.metadata.uid in pending]
            if left:
                return left
            job.status.pending_preemption_uids = []
            self._finish_preemption_drain(job, key)
            return []

        detail = None
        if self.scheduler is not None:
            preemptor = self.scheduler.eviction_requested(key)
            if preemptor is not None:
                detail = f"preempted by higher-priority TrainJob {preemptor}"
        if detail is None and self._chaos_preempts:
            d, ready = self._chaos_preempt_due(job)
            if d is not None and not ready:
                if self.heartbeat_source is None:
                    # No heartbeat source (operator without --log-dir):
                    # the directive can NEVER fire — warn once instead of
                    # fast-polling this job's sync forever.
                    if key not in self._chaos_preempt_warned:
                        self._chaos_preempt_warned.add(key)
                        self.cluster.record_event(
                            TrainJob.KIND, job.namespace, job.name,
                            "Warning", "ChaosPreemptUnarmed",
                            "preempt: directive targets this job but the "
                            "operator has no heartbeat source (--log-dir); "
                            "the step boundary can never be observed",
                        )
                else:
                    # Armed but the trainer has not reached the step yet:
                    # poll the heartbeat soon (chaos determinism beats
                    # efficiency).
                    self.queue.add_after(key, 0.3)
            elif d is not None:
                self._chaos_state.mark(d)
                detail = (f"chaos preempt directive fired at step >= "
                          f"{d.params['step']}")
        if detail is None:
            return None
        if is_terminal(job.status):
            # Raced completion: nothing to evict; drop the request.
            if self.scheduler is not None:
                self.scheduler.clear_eviction(key)
            return None

        now = self._now()
        # The eviction marker is deliberately NOT cleared here: it stands
        # ("eviction in progress") until requeue_preempted/release pops it,
        # so the preemptor's retry syncs can neither re-mark this victim
        # nor pick a second one while the drain is still in flight.
        job.status.preemptions += 1
        job.status.last_preemption_time = now
        metrics.sched_preemptions_total.labels(namespace=job.namespace).inc()
        doomed = [p for p in pods if p.status.phase != PodPhase.SUCCEEDED]
        self.cluster.record_event(
            TrainJob.KIND, job.namespace, job.name, "Normal",
            status_engine.REASON_PREEMPTED,
            f"Preempting TrainJob {key} ({detail}): gracefully evicting "
            f"{len(doomed)} pod(s) (SIGTERM -> emergency checkpoint); the "
            f"job will requeue and resume",
        )
        status_engine.set_condition(
            job.status, JobConditionType.PREEMPTED,
            status_engine.REASON_PREEMPTED,
            f"TrainJob {key} was preempted ({detail}); waiting to be "
            f"rescheduled.", now,
        )
        if doomed:
            job.status.pending_preemption_uids = sorted(
                p.metadata.uid for p in doomed
            )
            # The latch event lands BEFORE any pod.delete can: the
            # caller flushes the latch first, then deletes — so a
            # timeline showing latch -> pod.delete is the PR-17 write->
            # delete ordering made observable.
            journal_lib.get_journal().record(
                key, "preempt.latch", pods=len(doomed), detail=detail)
            return doomed
        self._finish_preemption_drain(job, key)
        return []

    def _finish_preemption_drain(self, job: TrainJob, key: str) -> None:
        """Every evicted pod is gone: hand the slice back (the preemptor
        is among the kick targets) and requeue this job — it resumes from
        its emergency checkpoint when capacity frees again."""
        if self.scheduler is not None:
            self.scheduler.requeue_preempted(job)  # journals preempt.requeue
            self._kick_slice_waiters()
        elif self.slice_allocator is not None:
            if self.slice_allocator.release(key):
                self._kick_slice_waiters()
            journal_lib.get_journal().record(key, "preempt.requeue")
        # Our own readmission attempt (chaos preemptions with idle
        # capacity readmit on this wake-up; scheduler-queued jobs get
        # their Queued position refreshed).
        self.queue.add_after(key, 0.2)

    def _gang_recovery_tick(self, job: TrainJob, pods: list[Pod],
                            key: str) -> list[Pod] | None:
        """One gang-recovery pass: consecutive-tally reset on heartbeat
        progress, then the two triggers — (a) a gang member failed with a
        retryable exit code under EXIT_CODE policy, (b) the hang watchdog
        (Running job whose freshest heartbeat is older than
        recovery.heartbeatTimeoutSeconds). Returns None when this sync
        did not initiate a gang restart or backoff-fail the job;
        otherwise the pods to delete (possibly none) — the caller skips
        the per-type loop and issues the deletes only AFTER the latch
        flush succeeds, so a stale fenced observation 409s before
        anything dies."""
        rec = job.spec.run_policy.recovery
        if rec.policy != "gang":
            return None  # per-pod replacement: today's path, bit-for-bit
        now = self._now()
        # Heartbeat aggregation hits per-pod files on disk: read at most
        # once per tick, and ONLY on the branches that consume it — a
        # healthy job with no watchdog and a clean tally pays zero
        # heartbeat I/O per sync.
        hb_memo: list[dict | None] = []

        def heartbeat() -> dict | None:
            if not hb_memo:
                hb_memo.append(self._job_heartbeat(job))
            return hb_memo[0]

        # Sustained progress resets the consecutive tally: a week-long job
        # eating occasional preemptions must not creep toward its
        # backoffLimit (the limit exists to stop futile crash-loops, and a
        # job that ADVANCES between failures is not looping).
        if job.status.consecutive_restarts > 0:
            hb = heartbeat()
            if hb is not None and hb.get("step") is not None:
                baseline = job.status.restart_heartbeat_step
                if baseline is None:
                    # The last counted restart couldn't read a heartbeat
                    # (torn file, collector hiccup): establish the baseline
                    # at the first readable step instead of treating it as
                    # 0 — a job crash-looping at step N would otherwise
                    # "advance" past the implicit 0 every lap and reset its
                    # tally forever, never exhausting backoffLimit. Step-0
                    # writes don't qualify: the trainer force-writes
                    # {step: 0} at startup BEFORE resuming its checkpoint,
                    # so a post-roll 0 is a generation marker, not a
                    # progress high-water — establishing on it would let
                    # the resume write (back at the checkpoint step, still
                    # short of the crash point) spuriously reset the tally.
                    if int(hb["step"]) > 0:
                        job.status.restart_heartbeat_step = int(hb["step"])
                elif hb["step"] >= baseline + max(
                        1, rec.progress_threshold_steps):
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Normal",
                        "RestartTallyReset",
                        f"Heartbeat advanced to step {hb['step']} (past "
                        f"{baseline}+{rec.progress_threshold_steps}): "
                        f"resetting consecutive restart count from "
                        f"{job.status.consecutive_restarts}",
                    )
                    job.status.consecutive_restarts = 0
                    job.status.restart_heartbeat_step = None
            else:
                # No step signal (heartbeat-less deployment): sustained
                # runtime is the progress proxy, or EXIT_CODE preemptions —
                # which the per-pod path never counted — would creep toward
                # backoffLimit forever. Youngest member's age, so a stray
                # older pod can't inflate the generation's runtime.
                started = [p.status.start_time
                           for p in self._gang_members(pods)
                           if p.status.start_time]
                if (started and now - max(started)
                        >= GANG_PROGRESS_FALLBACK_RUNTIME_S):
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Normal",
                        "RestartTallyReset",
                        f"Gang ran {int(now - max(started))}s without a "
                        f"heartbeat signal (fallback progress threshold "
                        f"{GANG_PROGRESS_FALLBACK_RUNTIME_S:g}s): resetting "
                        f"consecutive restart count from "
                        f"{job.status.consecutive_restarts}",
                    )
                    job.status.consecutive_restarts = 0
                    job.status.restart_heartbeat_step = None

        # A counted roll whose deletions are still in flight (apiserver
        # rejected some last pass; informer cache still lists a doomed
        # pod) is drained BEFORE any trigger logic: the triggering failed
        # pod may already be gone while a doomed survivor lingers, and
        # recreating peers next to an old-generation pod would build
        # exactly the mixed-generation gang this policy exists to prevent.
        # Re-issuing the deletes without re-counting also keeps flaky
        # deletes from inflating the tally/metric toward backoffLimit
        # (limit=N must mean N real gang restarts). The latch is the
        # doomed pods' uids, NOT the Restarting condition: a recreated
        # gang member failing anew (fresh uid) is a genuinely new failure
        # and must count, or a job crash-looping before ever reaching
        # Running would roll forever past its limit. It lives in status
        # (persisted with the tally in the same update) so an operator
        # failover mid-roll drains the survivors instead of re-entering
        # the trigger path on the still-Failed pod and re-counting the
        # same incident toward backoffLimit.
        pending = set(job.status.pending_gang_roll_uids)
        if pending:
            left = [p for p in pods if p.metadata.uid in pending]
            if left:
                return left
            job.status.pending_gang_roll_uids = []  # roll fully drained

        members = self._gang_members(pods)
        live = [p for p in members if not p.is_finished()]
        slices = tpu_env.num_slices(job)
        # Multi-slice jobs roll at SLICE granularity: a retryable failure
        # (or a hung heartbeat) dooms only the affected slice's gang while
        # the other slices hold at the trainer's DCN barrier — their pods
        # are never deleted, and the restarted slice's resume triggers the
        # survivors' in-process rewind to the shared checkpoint
        # (parallel/multislice.py). None = whole-gang roll (slices == 1).
        affected_slices: set[int] | None = None

        # Trigger (a): retryable gang-member failure. A NON-retryable
        # failure wins — fall through to the normal status machine, which
        # marks the job Failed (gang restarting around a permanent error
        # would just crash-loop the whole slice).
        trigger: tuple[str, str] | None = None  # (metric reason, detail)
        failed_retryable: list[Pod] = []
        for pod in members:
            if pod.status.phase != PodPhase.FAILED:
                continue
            rt = api_defaults.canonical_replica_type(
                pod.metadata.labels.get(ctrl.LABEL_REPLICA_TYPE, "")
            )
            spec = job.spec.replica_specs.get(rt) if rt is not None else None
            if spec is None or spec.restart_policy != RestartPolicy.EXIT_CODE:
                continue
            code = pod.main_exit_code()
            if code is None or not is_retryable_exit_code(code):
                return None  # permanent failure: normal path fails the job
            failed_retryable.append(pod)
            if trigger is None:
                # Same cause taxonomy as the per-pod path: 128+signum is
                # infrastructure (preemption/eviction) EXCEPT 138, the
                # app-declared restart request.
                infra = is_signal_exit(code) and code != EXIT_USER_RETRYABLE
                trigger = (
                    "preempt" if infra else "exit_code",
                    f"pod {pod.name} exited with retryable code {code}",
                )

        if trigger is not None and slices > 1:
            affected_slices = {
                s for s in (self._pod_slice(job, p) for p in failed_retryable)
                if s is not None
            } or None

        # Trigger (b): the hang watchdog. Armed only once a heartbeat
        # exists; staleness is measured against the freshest of (heartbeat
        # write, live pod start) so a just-rolled gang gets a full quiet
        # window to import/compile/resume before the clock can fire again.
        # Multi-slice jobs evaluate staleness PER SLICE (the collector's
        # per-replica map): one wedged slice rolls alone while the others
        # hold at the DCN barrier — their exchange loop keeps refreshing
        # their heartbeats, so they read fresh here by construction.
        if (trigger is None and rec.heartbeat_timeout_seconds
                and live and has_condition(job.status, JobConditionType.RUNNING)):
            hb = heartbeat()
            if hb is None:
                self.queue.add_after(key, rec.heartbeat_timeout_seconds)
            elif slices > 1 and hb.get("replicas"):
                per_pod = hb["replicas"]
                by_slice: dict[int, list[Pod]] = {}
                for p in live:
                    s = self._pod_slice(job, p)
                    if s is not None:
                        by_slice.setdefault(s, []).append(p)
                stale: set[int] = set()
                soonest: float | None = None
                for s, spods in sorted(by_slice.items()):
                    freshest = max(
                        [float((per_pod.get(p.name) or {}).get("t") or 0.0)
                         for p in spods]
                        + [p.status.start_time or p.metadata.creation_timestamp
                           for p in spods]
                    )
                    age = now - freshest
                    if age >= rec.heartbeat_timeout_seconds:
                        stale.add(s)
                    else:
                        left = rec.heartbeat_timeout_seconds - age
                        soonest = left if soonest is None else min(soonest, left)
                if stale:
                    names = ",".join(str(s) for s in sorted(stale))
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Warning",
                        status_engine.REASON_HEARTBEAT_STALE,
                        f"No trainer progress from slice(s) {names} for "
                        f">= {rec.heartbeat_timeout_seconds:g}s (job "
                        f"heartbeat at step {hb.get('step')}): treating "
                        f"the slice gang(s) as hung",
                    )
                    trigger = ("hang",
                               f"slice(s) {names} heartbeat stale at step "
                               f"{hb.get('step')}")
                    affected_slices = stale
                elif soonest is not None:
                    self.queue.add_after(key, soonest + 0.25)
            else:
                freshest = max(
                    [float(hb.get("t") or 0.0)]
                    + [p.status.start_time or p.metadata.creation_timestamp
                       for p in live]
                )
                age = now - freshest
                if age >= rec.heartbeat_timeout_seconds:
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Warning",
                        status_engine.REASON_HEARTBEAT_STALE,
                        f"No trainer progress for {int(age)}s (heartbeat at "
                        f"step {hb.get('step')}, "
                        f"heartbeatTimeoutSeconds="
                        f"{rec.heartbeat_timeout_seconds:g}): treating the "
                        f"job as hung",
                    )
                    trigger = (
                        "hang",
                        f"heartbeat stale for {int(age)}s at step "
                        f"{hb.get('step')}",
                    )
                else:
                    self.queue.add_after(
                        key, rec.heartbeat_timeout_seconds - age + 0.25
                    )

        if trigger is None:
            return None

        reason, detail = trigger
        limit = job.spec.run_policy.backoff_limit
        if limit is not None and job.status.consecutive_restarts >= limit:
            msg = (
                f"TrainJob {key} has exceeded its backoffLimit ({limit} "
                f"consecutive gang restarts without progress; last: {detail})"
            )
            self.cluster.record_event(
                TrainJob.KIND, job.namespace, job.name, "Warning",
                status_engine.REASON_BACKOFF_EXCEEDED, msg,
            )
            if status_engine.set_condition(
                job.status, JobConditionType.FAILED,
                status_engine.REASON_BACKOFF_EXCEEDED, msg, now,
            ):
                metrics.jobs_failed.labels(namespace=job.namespace).inc()
            if job.status.completion_time is None:
                job.status.completion_time = now
            return []

        # The restart: ONE tally increment and ONE restarts_total sample
        # however many pods roll, heartbeat high-water recorded as the
        # progress baseline the reset above compares against.
        job.status.consecutive_restarts += 1
        job.status.gang_restarts += 1
        hb = heartbeat()
        if hb is not None and hb.get("step") is not None:
            job.status.restart_heartbeat_step = int(hb["step"])
        metrics.restarts_total.labels(
            namespace=job.namespace, reason=reason
        ).inc()
        scope = ""
        if affected_slices:
            # Per-slice roll: only the failed slice(s)' gangs die; the
            # other slices' pods hold at the trainer's DCN barrier and
            # rewind in-process once the restarted slice resumes.
            doomed = [p for p in live
                      if self._pod_slice(job, p) in affected_slices]
            doomed += [p for p in failed_retryable if p not in doomed]
            for s in sorted(affected_slices):
                job.status.slice_restarts[str(s)] = (
                    job.status.slice_restarts.get(str(s), 0) + 1)
            scope = (" [slice(s) "
                     + ",".join(str(s) for s in sorted(affected_slices))
                     + f" of {slices}; other slices hold at the barrier]")
        else:
            doomed = live + failed_retryable
        self.cluster.record_event(
            TrainJob.KIND, job.namespace, job.name, "Normal",
            status_engine.REASON_GANG_RESTART,
            f"Gang restart #{job.status.gang_restarts} ({detail}){scope}: "
            f"deleting {len(doomed)} pod(s); consecutive restarts without "
            f"progress: {job.status.consecutive_restarts}",
        )
        status_engine.record_gang_restart(
            job,
            f"TrainJob {key} is gang-restarting: {detail}.",
            now,
        )
        job.status.pending_gang_roll_uids = sorted(
            p.metadata.uid for p in doomed
        )
        journal_lib.get_journal().record(
            key, "gang.roll", reason=reason, detail=detail,
            pods=len(doomed), restarts=job.status.gang_restarts)
        return doomed

    def _delete_gang_pods(self, job: TrainJob, key: str,
                          doomed: list[Pod]) -> None:
        for pod in doomed:
            self._tracked_delete_pod(job, pod)

    # ---------------------------------------------------------- limit checks

    def _release_capacity(self, key: str) -> None:
        """Free the job's slice claim (terminal/suspend/delete) and wake
        whoever can use it."""
        freed = False
        if self.scheduler is not None:
            freed = self.scheduler.release(key)
        elif self.slice_allocator is not None:
            freed = self.slice_allocator.release(key)
        if freed:
            self._kick_slice_waiters()

    def _kick_slice_waiters(self) -> None:
        """A slice was just freed (job finished/suspended/deleted): wake
        the waiters immediately instead of leaving them to the
        SLICE_RETRY_DELAY_S backoff. With a fleet scheduler, wake exactly
        the jobs the freed capacity can serve (in admission order) — the
        old shotgun re-listed and re-enqueued EVERY job per release, which
        is O(n²) sync work at 10k concurrent jobs."""
        if self.scheduler is not None:
            for key in self.scheduler.kick_targets():
                # A freed slice may serve the OTHER kind's waiter (a
                # serve-replica claim): route by key shape.
                self.route_enqueue(key)
            return
        try:
            # Read-only lister snapshot (round 17): this scheduler-less
            # fallback fires per slice release — a full deep-copying
            # LIST here was O(fleet) per freed slice.
            jobs = self.cluster.snapshot_jobs()
        except Exception:
            return
        for j in jobs:
            if (j.spec.tpu is not None and j.spec.tpu.topology
                    and not is_terminal(j.status)):
                self.enqueue(naming.job_key(j.namespace, j.name))

    def _past_limits(self, job: TrainJob, pods: list[Pod]) -> tuple[bool, str, str]:
        if self._past_active_deadline(job):
            return (
                True,
                status_engine.REASON_DEADLINE_EXCEEDED,
                f"TrainJob {job.key()} has exceeded its activeDeadlineSeconds "
                f"({job.spec.run_policy.active_deadline_seconds}s)",
            )
        if self._past_backoff_limit(job, pods):
            return (
                True,
                status_engine.REASON_BACKOFF_EXCEEDED,
                f"TrainJob {job.key()} has exceeded its backoffLimit "
                f"({job.spec.run_policy.backoff_limit} restarts)",
            )
        return False, "", ""

    def _past_active_deadline(self, job: TrainJob) -> bool:
        """pastActiveDeadline (controller.go:539)."""
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is None or job.status.start_time is None:
            return False
        return self._now() - job.status.start_time >= deadline

    def _past_backoff_limit(self, job: TrainJob, pods: list[Pod]) -> bool:
        """pastBackoffLimit (controller.go:500-536): container restart counts
        are only accumulated for replicas whose policy is OnFailure/Always —
        Never/ExitCode replicas fail/restart via pod replacement instead."""
        limit = job.spec.run_policy.backoff_limit
        if limit is None:
            return False
        restarts = 0
        for rtype, spec in job.spec.replica_specs.items():
            if spec.restart_policy not in (RestartPolicy.ON_FAILURE, RestartPolicy.ALWAYS):
                continue
            for pod in self.filter_pods_for_replica_type(pods, str(rtype)):
                if pod.status.phase in (PodPhase.RUNNING, PodPhase.PENDING):
                    restarts += sum(
                        cs.restart_count for cs in pod.status.container_statuses
                    )
        if limit == 0:
            return restarts > 0
        return restarts >= limit

    # ------------------------------------------------------------- terminal

    def _delete_pods_and_services(self, job: TrainJob, pods: list[Pod], services: list[Service]) -> None:
        """deletePodsAndServices (job.go:155-179). Fork behavior: a FAILED
        job keeps everything for debugging (job.go:162) when keep_failed_pods."""
        policy = job.spec.run_policy.clean_pod_policy or CleanPodPolicy.RUNNING
        if policy == CleanPodPolicy.NONE:
            return
        if self.keep_failed_pods and is_failed(job.status):
            return
        for pod in pods:
            if policy == CleanPodPolicy.RUNNING and pod.status.phase not in (
                PodPhase.RUNNING,
                PodPhase.PENDING,
            ):
                continue
            self.pod_control.delete_pod(pod.namespace, pod.name, job)
        # Services have no "running" notion: any cleanup policy removes them
        # together with the pods (ref job.go:171-178 deletes services with All
        # and Running alike).
        for svc in services:
            self.service_control.delete_service(svc.namespace, svc.name, job)

    def _effective_ttl(self, job: TrainJob) -> int:
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is not None:
            return int(ttl)
        clean = (
            job.spec.run_policy.clean_pod_policy == CleanPodPolicy.ALL
            and not is_failed(job.status)
        )
        if clean:
            return getenv_int(ENV_TTL_CLEAN, DEFAULT_TTL_CLEAN_S)
        return getenv_int(ENV_TTL_DEBUG, DEFAULT_TTL_DEBUG_S)

    def _cleanup_by_ttl(self, job: TrainJob) -> None:
        """cleanupTFJob (job.go:181-219): delete the job ttl seconds after
        completion, else schedule a delayed re-sync."""
        if job.status.completion_time is None:
            return
        ttl = self._effective_ttl(job)
        if ttl < 0:
            return
        expiry = job.status.completion_time + ttl
        now = self._now()
        if now >= expiry:
            try:
                self.cluster.delete_job(job.namespace, job.name)
            except Exception as e:
                # Likely a delete race (already gone) — but a real
                # apiserver error must retry, not strand the job past its
                # TTL forever (tpulint TPH101: no silent broad excepts in
                # reconcile paths).
                logger_for_key(job.key()).info("ttl delete failed: %s", e)
                self.queue.add_after(job.key(), 1.0)
        else:
            self.queue.add_after(job.key(), expiry - now + 0.1)

    # ------------------------------------------------------------- replicas

    def reconcile_pods(
        self, job: TrainJob, pods: list[Pod], rtype: ReplicaType, spec: ReplicaSpec
    ) -> None:
        """reconcilePods (pod.go:89-170) + elastic scaling (beyond the
        reference, which keeps replica counts static — SURVEY §5)."""
        replicas = int(spec.replicas or 0)
        rpods = self.filter_pods_for_replica_type(pods, str(rtype))
        slices = self.get_pod_slices(rpods, replicas)
        key = job.key()
        exp_key = naming.gen_expectation_pods_key(key, str(rtype))

        restart = False
        worker0_completed = self._worker0_completed(job, pods)
        masters_present = status_engine.has_chief_or_master(job)
        spec_hash = tf_config.topology_hash(job)
        # Two-phase roll: while ANY live pod of this job (any type) still
        # carries a stale topology, hold replacement creations. Mixing
        # generations is not just wasteful — a new worker can dial the OLD
        # generation's jax.distributed coordinator on the reused port and
        # abort the whole gang ("unexpected incarnation"). Deletes below
        # proceed; their events re-sync and creation happens once the old
        # generation is gone.
        stale_live = any(
            p.metadata.labels.get(ctrl.LABEL_SPEC_HASH) not in (None, spec_hash)
            and not p.is_finished()
            for p in pods
        )

        # Scale-down: replicas beyond the (possibly just lowered) count are
        # removed — without this, a spec edit orphans live trainers forever.
        self._delete_out_of_range(
            job, rpods, replicas, exp_key, self.pod_control.delete_pod,
            event_reason="ScaleDown",
        )

        for index, pod_slice in enumerate(slices):
            if not pod_slice:
                if stale_live:
                    continue  # old generation still draining (see above)
                master_role = (
                    rtype in (ReplicaType.CHIEF, ReplicaType.MASTER)
                    if masters_present
                    else (rtype is ReplicaType.WORKER and index == 0)
                )
                self._create_new_pod(job, rtype, index, spec, master_role)
                continue
            if len(pod_slice) > 1:
                # Duplicate index: keep the oldest, delete the rest.
                pod_slice.sort(key=lambda p: p.metadata.creation_timestamp)
                for dup in pod_slice[1:]:
                    self.expectations.raise_expectations(exp_key, 0, 1)
                    if not self.pod_control.delete_pod(dup.namespace, dup.name, job):
                        self.expectations.deletion_observed(exp_key)
            pod = pod_slice[0]

            # Rolling re-injection: a live pod created under a different
            # topology (old replica count / mesh / slice) carries a stale
            # TF_CONFIG + TPU env, which are injected at creation and cannot
            # be updated in place. Replace it; trainers resume from their
            # checkpoints at the new world size (models/train.py auto-resume).
            # Finished pods keep their history; unlabeled pods (pre-feature)
            # are left alone.
            pod_hash = pod.metadata.labels.get(ctrl.LABEL_SPEC_HASH)
            if (pod_hash is not None and pod_hash != spec_hash
                    and not pod.is_finished()):
                self.cluster.record_event(
                    TrainJob.KIND, job.namespace, job.name, "Normal",
                    "TopologyChanged",
                    f"Rolling pod {pod.name}: topology {pod_hash} -> "
                    f"{spec_hash}",
                )
                restart = True
                self.expectations.raise_expectations(exp_key, 0, 1)
                if not self.pod_control.delete_pod(pod.namespace, pod.name, job):
                    self.expectations.deletion_observed(exp_key)
                continue

            # Exit-code restart: a failed pod whose training container exited
            # with a retryable code is deleted; the next sync recreates it
            # (pod.go:135-156 + train_util.go:18).
            if (
                spec.restart_policy == RestartPolicy.EXIT_CODE
                and pod.status.phase == PodPhase.FAILED
            ):
                code = pod.main_exit_code()
                if code is not None and is_retryable_exit_code(code):
                    self.cluster.record_event(
                        TrainJob.KIND, job.namespace, job.name, "Normal",
                        "ExitedWithCode",
                        f"Pod {pod.name} exited with code {code}; restarting",
                    )
                    # Cause-labeled restart accounting: 128+signum means
                    # the infrastructure killed it (preemption/eviction —
                    # the trainer's graceful-SIGTERM path exits 143 here),
                    # EXCEPT 138 (SIGUSR1), which is the app asking for its
                    # own restart; unknown retryable non-signal codes land
                    # as exit_code too.
                    infra = (is_signal_exit(code)
                             and code != EXIT_USER_RETRYABLE)
                    metrics.restarts_total.labels(
                        namespace=job.namespace,
                        reason="preempt" if infra else "exit_code",
                    ).inc()
                    # The restart decision stands even if the delete races a
                    # concurrent out-of-band removal: either way the replica
                    # is being replaced, not permanently failed.
                    restart = True
                    self.expectations.raise_expectations(exp_key, 0, 1)
                    if not self.pod_control.delete_pod(pod.namespace, pod.name, job):
                        # Pod already gone: its delete event (if any) fired
                        # before our expectation was raised; roll it back.
                        self.expectations.deletion_observed(exp_key)

        status_engine.update_replica_status_counts(
            job.status, rtype, self.filter_pods_for_replica_type(pods, str(rtype))
        )
        status_engine.update_status_single(
            job, rtype, replicas, restart, worker0_completed, self._now()
        )

    def _worker0_completed(self, job: TrainJob, pods: list[Pod]) -> bool:
        """worker-0 success detection (pod.go:159-162)."""
        for pod in self.filter_pods_for_replica_type(pods, str(ReplicaType.WORKER)):
            if pod.metadata.labels.get(ctrl.LABEL_REPLICA_INDEX) == "0":
                if pod.status.phase == PodPhase.SUCCEEDED:
                    return True
                code = pod.main_exit_code()
                if code == 0 and pod.is_finished():
                    return True
        return False

    def _create_new_pod(
        self,
        job: TrainJob,
        rtype: ReplicaType,
        index: int,
        spec: ReplicaSpec,
        master_role: bool,
    ) -> None:
        """createNewPod (pod.go:171-258)."""
        template = copy.deepcopy(spec.template)
        labels = {
            **template.labels,
            **ctrl.gen_labels(job.name),
            ctrl.LABEL_REPLICA_TYPE: str(rtype).lower(),
            ctrl.LABEL_REPLICA_INDEX: str(index),
            ctrl.LABEL_SPEC_HASH: tf_config.topology_hash(job),
        }
        if master_role:
            labels[ctrl.LABEL_JOB_ROLE] = "master"
        if tpu_env.num_slices(job) > 1 and tpu_env.is_spmd_replica(rtype):
            pid = tpu_env.process_id(job, rtype, index)
            if pid is not None:
                labels[ctrl.LABEL_SLICE_ID] = str(
                    tpu_env.slice_of_process(job, pid))

        name = naming.gen_general_name(job.name, str(rtype), index)

        # Cluster-spec injection into the training container (pod.go:208,260).
        container = api_defaults.training_container(spec)
        tgt = template.container(container.name) if container is not None else None
        if tgt is not None:
            if tf_config.is_distributed(job):
                tgt.set_env(tf_config.ENV_TF_CONFIG, tf_config.gen_tf_config(job, rtype, index))
            for k, v in tpu_env.gen_tpu_env(job, rtype, index).items():
                tgt.set_env(k, v)
            # TPU resources for SPMD pods (reference copied templates verbatim
            # and left GPU resources to the user; the TPU slice is ours to wire).
            chips = tpu_env.tpu_resource_count(job)
            if chips is not None and tpu_env.is_spmd_replica(rtype):
                tgt.resources.setdefault(tpu_env.TPU_RESOURCE, chips)

        # Fork `((index))` subPath substitution (pod.go:50-85): each replica
        # mounts its own data shard.
        for c in template.containers:
            for vm in c.volume_mounts:
                if "((index))" in vm.sub_path:
                    vm.sub_path = vm.sub_path.replace("((index))", str(index))

        # Restart policy mapping (setRestartPolicy, pod.go:315): ExitCode is
        # operator-managed, so the pod itself must not restart.
        if spec.restart_policy == RestartPolicy.EXIT_CODE:
            template.restart_policy = "Never"
        elif spec.restart_policy is not None:
            template.restart_policy = str(spec.restart_policy)

        annotations = dict(template.annotations)
        scheduler_name = template.scheduler_name
        if self.enable_gang and job.spec.run_policy.scheduling.gang:
            scheduler_name = self.gang_scheduler_name
            annotations[gang.ANNOTATION_GROUP_NAME] = naming.gen_podgroup_name(job.name)
        template.annotations = annotations

        pod = Pod(
            metadata=ObjectMeta(
                name=name,
                namespace=job.namespace,
                labels=labels,
                annotations=annotations,
            ),
            spec=template,
            scheduler_name=scheduler_name,
        )
        self._tracked_create_pod(job, pod, str(rtype))

    # ------------------------------------------------------------- services

    def reconcile_services(
        self, job: TrainJob, services: list[Service], rtype: ReplicaType, spec: ReplicaSpec
    ) -> None:
        """reconcileServices (service.go:35-128): one headless service per
        replica gives each process its stable DNS identity."""
        replicas = int(spec.replicas or 0)
        rsvcs = self.filter_services_for_replica_type(services, str(rtype))
        slices = self.get_service_slices(rsvcs, replicas)
        exp_key = naming.gen_expectation_services_key(job.key(), str(rtype))

        # Scale-down: drop DNS identities beyond the current replica count.
        self._delete_out_of_range(
            job, rsvcs, replicas, exp_key, self.service_control.delete_service
        )

        for index, svc_slice in enumerate(slices):
            if svc_slice:
                continue
            name = naming.gen_general_name(job.name, str(rtype), index)
            selector = {
                **ctrl.gen_labels(job.name),
                ctrl.LABEL_REPLICA_TYPE: str(rtype).lower(),
                ctrl.LABEL_REPLICA_INDEX: str(index),
            }
            svc = Service(
                metadata=ObjectMeta(
                    name=name, namespace=job.namespace, labels=dict(selector)
                ),
                selector=selector,
                ports=[
                    ServicePort(
                        name=api_defaults.DEFAULT_PORT_NAME,
                        port=tf_config.replica_port(job, rtype),
                    ),
                    ServicePort(
                        name=api_defaults.COORDINATOR_PORT_NAME,
                        port=tf_config.replica_port(
                            job, rtype, api_defaults.COORDINATOR_PORT_NAME
                        ),
                    ),
                ],
            )
            self._tracked_create_service(job, svc, str(rtype))
