"""Kubernetes API-server substrate adapter.

The reference operator IS a K8s API client (generated clientsets + shared
informers, SURVEY.md §1 L2/L3). This adapter gives the same core that runs
on the in-memory substrate a real-cluster deployment: the identical
`Cluster` method surface (core/cluster.py) implemented over the API
server's REST protocol with plain stdlib HTTP — no client library — plus
list+watch informer threads that replay the server's event stream into the
substrate's synchronous add/update/delete handlers.

Wire mapping:
  TrainJob  <-> CR   apis/tpujob.dev/v1/.../trainjobs (+ /status subresource)
  Pod       <-> core v1 Pod          (api/v1/.../pods)
  Service   <-> core v1 Service      (api/v1/.../services, headless)
  PodGroup  <-> scheduling.volcano.sh/v1beta1 podgroups (gang admission)
  Event     <-> core v1 Event        (involvedObject-keyed, best-effort)

Auth: bearer token + CA (in-cluster service account files, or explicit
arguments); `insecure=True` skips TLS verification for dev clusters. The
fake API server in testing/fake_apiserver.py speaks the same subset for
Tier-2 wire-protocol tests without a cluster.
"""

from __future__ import annotations

import copy
import http.client
import json
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable

from tf_operator_tpu.api import compat
from tf_operator_tpu.api.types import (
    ContainerPort,
    ContainerSpec,
    EnvVar,
    JobCondition,
    JobConditionType,
    JobStatus,
    ObjectMeta,
    OwnerReference,
    PodTemplateSpec,
    ReplicaStatus,
    TrainJob,
    VolumeMount,
)
from tf_operator_tpu.core.cluster import (
    KIND_INFSVC,
    KIND_JOB,
    KIND_POD,
    KIND_PODGROUP,
    KIND_SERVICE,
    AlreadyExistsError,
    ApiError,
    ConflictError,
    ContainerStatus,
    Event,
    GoneError,
    NotFoundError,
    Pod,
    PodGroup,
    PodPhase,
    PodStatus,
    Service,
    ServicePort,
)
from tf_operator_tpu.status import metrics
from tf_operator_tpu.utils.logging import FieldLogger

PODGROUP_API = "scheduling.volcano.sh/v1beta1"

# ---------------------------------------------------------------------------
# Serialization: substrate dataclasses <-> K8s JSON
# ---------------------------------------------------------------------------


def _meta_to_dict(meta: ObjectMeta) -> dict:
    out: dict[str, Any] = {
        "name": meta.name,
        "namespace": meta.namespace,
        "labels": meta.labels,
        "annotations": meta.annotations,
    }
    if meta.uid:
        out["uid"] = meta.uid
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.owner_references:
        out["ownerReferences"] = [
            {
                "apiVersion": r.api_version,
                "kind": r.kind,
                "name": r.name,
                "uid": r.uid,
                "controller": r.controller,
                "blockOwnerDeletion": r.block_owner_deletion,
            }
            for r in meta.owner_references
        ]
    return out


def _parse_time(v) -> float | None:
    """K8s RFC3339 timestamp (or our fake's float) -> epoch seconds."""
    if v in (None, ""):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime

    try:
        return datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


def _meta_from_dict(d: dict) -> ObjectMeta:
    rv = d.get("resourceVersion", 0)
    try:
        rv = int(rv)
    except (TypeError, ValueError):
        rv = 0
    return ObjectMeta(
        name=d.get("name", ""),
        namespace=d.get("namespace", "default"),
        uid=d.get("uid", ""),
        labels=dict(d.get("labels") or {}),
        annotations=dict(d.get("annotations") or {}),
        resource_version=rv,
        # A finalizer-held object is served with deletionTimestamp set; the
        # controller's adopt guard (controller.py "unless being deleted")
        # depends on seeing it.
        deletion_timestamp=_parse_time(d.get("deletionTimestamp")),
        owner_references=[
            OwnerReference(
                api_version=r.get("apiVersion", ""),
                kind=r.get("kind", ""),
                name=r.get("name", ""),
                uid=r.get("uid", ""),
                controller=bool(r.get("controller", False)),
                block_owner_deletion=bool(r.get("blockOwnerDeletion", False)),
            )
            for r in d.get("ownerReferences") or []
        ],
    )


def job_status_to_dict(status: JobStatus) -> dict:
    return {
        "conditions": [
            {
                "type": str(c.type),
                "status": "True" if c.status else "False",
                "reason": c.reason,
                "message": c.message,
                "lastUpdateTime": c.last_update_time,
                "lastTransitionTime": c.last_transition_time,
            }
            for c in status.conditions
        ],
        "replicaStatuses": {
            str(rt): {"active": rs.active, "succeeded": rs.succeeded,
                      "failed": rs.failed}
            for rt, rs in status.replica_statuses.items()
        },
        "startTime": status.start_time,
        "completionTime": status.completion_time,
        # Gang-recovery bookkeeping: the consecutive tally/heartbeat
        # baseline must survive operator failover (the whole point of a
        # CONSECUTIVE counter is that it persists until progress, not
        # until the next leader election).
        "gangRestarts": status.gang_restarts,
        "consecutiveRestarts": status.consecutive_restarts,
        "restartHeartbeatStep": status.restart_heartbeat_step,
        "pendingGangRollUids": list(status.pending_gang_roll_uids),
        # Multi-slice: per-slice roll counts (visibility — which slice
        # keeps failing); the job-level tallies above stay authoritative
        # for backoffLimit.
        "sliceRestarts": dict(status.slice_restarts),
        "stuckPendingPods": list(status.stuck_pending_pods),
        # Preemption bookkeeping (sched/): count + cooldown anchor + drain
        # latch must survive operator failover exactly like the gang-roll
        # latch above (a new leader re-issues eviction deletes without
        # re-counting the incident).
        "preemptions": status.preemptions,
        "lastPreemptionTime": status.last_preemption_time,
        "pendingPreemptionUids": list(status.pending_preemption_uids),
        # Elastic reshape state: the effective degraded size must survive
        # failover (a new leader serving the spec size would roll the
        # reshaped gang back up onto capacity that is not there).
        "reshapedReplicas": status.reshaped_replicas,
        "reshapedTopology": status.reshaped_topology,
        # Slice claim record (moved out of the tpujob.dev/slice annotation
        # so the whole per-job lifecycle ships in ONE /status patch).
        "sliceIds": list(status.slice_ids),
    }


def job_status_from_dict(d: dict) -> JobStatus:
    from tf_operator_tpu.api.defaults import canonical_replica_type

    status = JobStatus(
        start_time=d.get("startTime"),
        completion_time=d.get("completionTime"),
        gang_restarts=int(d.get("gangRestarts") or 0),
        consecutive_restarts=int(d.get("consecutiveRestarts") or 0),
        restart_heartbeat_step=d.get("restartHeartbeatStep"),
        pending_gang_roll_uids=list(d.get("pendingGangRollUids") or []),
        slice_restarts={str(k): int(v) for k, v in
                        (d.get("sliceRestarts") or {}).items()},
        stuck_pending_pods=list(d.get("stuckPendingPods") or []),
        preemptions=int(d.get("preemptions") or 0),
        last_preemption_time=d.get("lastPreemptionTime"),
        pending_preemption_uids=list(d.get("pendingPreemptionUids") or []),
        reshaped_replicas=d.get("reshapedReplicas"),
        reshaped_topology=d.get("reshapedTopology") or "",
        slice_ids=list(d.get("sliceIds") or []),
    )
    for c in d.get("conditions") or []:
        status.conditions.append(
            JobCondition(
                type=JobConditionType(c["type"]),
                status=str(c.get("status")) == "True",
                reason=c.get("reason", ""),
                message=c.get("message", ""),
                last_update_time=c.get("lastUpdateTime") or 0.0,
                last_transition_time=c.get("lastTransitionTime") or 0.0,
            )
        )
    for rt, rs in (d.get("replicaStatuses") or {}).items():
        status.replica_statuses[canonical_replica_type(rt)] = ReplicaStatus(
            active=rs.get("active", 0),
            succeeded=rs.get("succeeded", 0),
            failed=rs.get("failed", 0),
        )
    return status


def infsvc_status_to_dict(status) -> dict:
    """InferenceService status wire form. Like the TrainJob status, the
    autoscaler's state (desiredReplicas + the lowLoadSince hysteresis
    latch) must survive operator failover — a new leader serving the
    spec floor would collapse a scaled-up service mid-burst."""
    return {
        "conditions": [
            {
                "type": str(c.type),
                "status": "True" if c.status else "False",
                "reason": c.reason,
                "message": c.message,
                "lastUpdateTime": c.last_update_time,
                "lastTransitionTime": c.last_transition_time,
            }
            for c in status.conditions
        ],
        "replicas": status.replicas,
        "readyReplicas": status.ready_replicas,
        "desiredReplicas": status.desired_replicas,
        "lastScaleTime": status.last_scale_time,
        "lowLoadSince": status.low_load_since,
        "restarts": status.restarts,
        "routerEndpoint": status.router_endpoint,
        "routerEndpoints": list(status.router_endpoints),
        "startTime": status.start_time,
    }


def infsvc_status_from_dict(d: dict):
    from tf_operator_tpu.api.types import InferenceServiceStatus

    status = InferenceServiceStatus(
        replicas=int(d.get("replicas") or 0),
        ready_replicas=int(d.get("readyReplicas") or 0),
        desired_replicas=d.get("desiredReplicas"),
        last_scale_time=d.get("lastScaleTime"),
        low_load_since=d.get("lowLoadSince"),
        restarts=int(d.get("restarts") or 0),
        router_endpoint=d.get("routerEndpoint"),
        router_endpoints=list(d.get("routerEndpoints") or []),
        start_time=d.get("startTime"),
    )
    for c in d.get("conditions") or []:
        status.conditions.append(
            JobCondition(
                type=JobConditionType(c["type"]),
                status=str(c.get("status")) == "True",
                reason=c.get("reason", ""),
                message=c.get("message", ""),
                last_update_time=c.get("lastUpdateTime") or 0.0,
                last_transition_time=c.get("lastTransitionTime") or 0.0,
            )
        )
    return status


def infsvc_to_k8s(svc) -> dict:
    out = compat.infsvc_to_dict(svc)
    out["metadata"] = _meta_to_dict(svc.metadata)
    out["status"] = infsvc_status_to_dict(svc.status)
    return _omit_nulls(out)


def infsvc_from_k8s(d: dict):
    svc = compat.infsvc_from_dict(d, apply_defaults=False)
    svc.metadata = _meta_from_dict(d.get("metadata") or {})
    svc.status = infsvc_status_from_dict(d.get("status") or {})
    return svc


def _omit_nulls(v):
    """Drop None-valued object fields, recursively — client-go's omitempty.
    A real apiserver rejects explicit `null` for non-nullable CRD fields
    (and the conformance-hardened fake does too); unset must mean absent."""
    if isinstance(v, dict):
        return {k: _omit_nulls(x) for k, x in v.items() if x is not None}
    if isinstance(v, list):
        return [_omit_nulls(x) for x in v]
    return v


_ABSENT = object()


def _wire_diff(new_d: dict, base_d: dict) -> dict:
    """Top-level merge-patch diff: the keys of `new_d` whose value differs
    from `base_d`, plus explicit nulls for keys that disappeared (RFC 7386
    null deletes). Byte-identical wire forms diff to {} — the no-op-skip
    signal the coalescing status writer keys off."""
    out = {k: v for k, v in new_d.items() if base_d.get(k, _ABSENT) != v}
    for k in base_d:
        if k not in new_d:
            out[k] = None
    return out


def job_to_k8s(job: TrainJob) -> dict:
    out = compat.job_to_dict(job)
    out["metadata"] = _meta_to_dict(job.metadata)
    out["status"] = job_status_to_dict(job.status)
    return _omit_nulls(out)


def job_from_k8s(d: dict) -> TrainJob:
    job = compat.job_from_dict(d, apply_defaults=False)
    job.metadata = _meta_from_dict(d.get("metadata") or {})
    job.status = job_status_from_dict(d.get("status") or {})
    return job


def _container_to_dict(c: ContainerSpec) -> dict:
    return {
        "name": c.name,
        "image": c.image,
        "command": list(c.command),
        "args": list(c.args),
        "env": [{"name": e.name, "value": e.value} for e in c.env],
        "ports": [
            {"name": p.name, "containerPort": p.container_port} for p in c.ports
        ],
        "resources": {"limits": c.resources} if c.resources else {},
        "volumeMounts": [
            {"name": v.name, "mountPath": v.mount_path, "subPath": v.sub_path,
             "readOnly": v.read_only}
            for v in c.volume_mounts
        ],
        "workingDir": c.working_dir,
    }


def _container_from_dict(d: dict) -> ContainerSpec:
    return ContainerSpec(
        name=d.get("name", ""),
        image=d.get("image", ""),
        command=list(d.get("command") or []),
        args=list(d.get("args") or []),
        env=[EnvVar(e.get("name", ""), e.get("value", ""))
             for e in d.get("env") or []],
        ports=[
            ContainerPort(p.get("name", ""), p.get("containerPort", 0))
            for p in d.get("ports") or []
        ],
        resources=dict((d.get("resources") or {}).get("limits") or {}),
        volume_mounts=[
            VolumeMount(
                name=v.get("name", ""), mount_path=v.get("mountPath", ""),
                sub_path=v.get("subPath", ""), read_only=bool(v.get("readOnly")),
            )
            for v in d.get("volumeMounts") or []
        ],
        working_dir=d.get("workingDir", ""),
    )


def pod_to_k8s(pod: Pod) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": _meta_to_dict(pod.metadata),
        "spec": {
            "containers": [_container_to_dict(c) for c in pod.spec.containers],
            "restartPolicy": pod.spec.restart_policy or "Never",
            "schedulerName": pod.scheduler_name or pod.spec.scheduler_name,
            "nodeName": pod.node_name,
            "nodeSelector": pod.spec.node_selector,
            "volumes": [
                {
                    "name": v.name,
                    **(
                        {"hostPath": {"path": v.host_path}} if v.host_path
                        else {"persistentVolumeClaim": {"claimName": v.claim_name}}
                        if v.claim_name else {"emptyDir": {}}
                    ),
                }
                for v in pod.spec.volumes
            ],
        },
        "status": {
            "phase": str(pod.status.phase),
            "containerStatuses": [
                {
                    "name": cs.name,
                    "restartCount": cs.restart_count,
                    **(
                        {"state": {"terminated": {"exitCode": cs.exit_code}}}
                        if cs.exit_code is not None
                        else {"state": {"running": {}}} if cs.running else {}
                    ),
                }
                for cs in pod.status.container_statuses
            ],
            "startTime": pod.status.start_time,
        },
    }


def pod_from_k8s(d: dict) -> Pod:
    from tf_operator_tpu.api.types import Volume

    spec_d = d.get("spec") or {}
    status_d = d.get("status") or {}
    statuses = []
    for cs in status_d.get("containerStatuses") or []:
        state = cs.get("state") or {}
        term = state.get("terminated") or {}
        statuses.append(
            ContainerStatus(
                name=cs.get("name", ""),
                running="running" in state,
                exit_code=term.get("exitCode"),
                restart_count=cs.get("restartCount", 0),
                reason=term.get("reason", ""),
            )
        )
    phase = status_d.get("phase") or "Pending"
    try:
        phase = PodPhase(phase)
    except ValueError:
        # K8s has phases we don't model ("Unknown" on NotReady nodes):
        # treat as not-finished rather than poisoning the informer.
        phase = PodPhase.PENDING
    return Pod(
        metadata=_meta_from_dict(d.get("metadata") or {}),
        spec=PodTemplateSpec(
            containers=[
                _container_from_dict(c) for c in spec_d.get("containers") or []
            ],
            volumes=[
                Volume(
                    name=v.get("name", ""),
                    host_path=(v.get("hostPath") or {}).get("path", ""),
                    claim_name=(v.get("persistentVolumeClaim") or {}).get(
                        "claimName", ""
                    ),
                    empty_dir="emptyDir" in v,
                )
                for v in spec_d.get("volumes") or []
            ],
            restart_policy=spec_d.get("restartPolicy", ""),
            scheduler_name=spec_d.get("schedulerName", ""),
            node_selector=dict(spec_d.get("nodeSelector") or {}),
        ),
        status=PodStatus(
            phase=phase,
            container_statuses=statuses,
            start_time=status_d.get("startTime"),
        ),
        scheduler_name=spec_d.get("schedulerName", ""),
        node_name=spec_d.get("nodeName", ""),
    )


def service_to_k8s(svc: Service) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": _meta_to_dict(svc.metadata),
        "spec": {
            "clusterIP": svc.cluster_ip,
            "selector": svc.selector,
            "ports": [{"name": p.name, "port": p.port} for p in svc.ports],
        },
    }


def service_from_k8s(d: dict) -> Service:
    spec_d = d.get("spec") or {}
    return Service(
        metadata=_meta_from_dict(d.get("metadata") or {}),
        selector=dict(spec_d.get("selector") or {}),
        ports=[
            ServicePort(p.get("name", ""), p.get("port", 0))
            for p in spec_d.get("ports") or []
        ],
        cluster_ip=spec_d.get("clusterIP", "None"),
    )


def podgroup_to_k8s(pg: PodGroup) -> dict:
    return {
        "apiVersion": PODGROUP_API,
        "kind": "PodGroup",
        "metadata": _meta_to_dict(pg.metadata),
        "spec": {"minMember": pg.min_member, "queue": pg.queue},
    }


def podgroup_from_k8s(d: dict) -> PodGroup:
    spec_d = d.get("spec") or {}
    return PodGroup(
        metadata=_meta_from_dict(d.get("metadata") or {}),
        min_member=spec_d.get("minMember", 0),
        queue=spec_d.get("queue", ""),
    )


# ---------------------------------------------------------------------------
# Raw API-server client
# ---------------------------------------------------------------------------

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class _TokenBucket:
    """client-go-style flowcontrol token bucket: `qps` refill rate, `burst`
    capacity. acquire() blocks until a token is available, so every caller
    (reconcile workers, informer relists, status writers) shares one
    client-side ceiling on API-server request rate — the reference's
    --qps/--burst RESTClient throttle (options.go:40-43,81-82). Thread-safe."""

    def __init__(self, qps: float, burst: int):
        self.qps = float(qps)
        self.burst = float(max(1, burst))
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> float:
        """Take one token, sleeping as needed. Returns seconds slept."""
        slept = 0.0
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return slept
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)
            slept += wait


def _path_resource(path: str) -> str:
    """Resource plural from an apiserver path, for the per-kind request
    metric: /apis/{group}/{ver}/namespaces/{ns}/{resource}/... (and the
    /api/{ver}/... core-group and cluster-scope forms)."""
    segs = [s for s in path.split("?", 1)[0].split("/") if s]
    base = 3 if segs and segs[0] == "apis" else 2
    if len(segs) <= base:
        return segs[-1] if segs else "?"
    if segs[base] == "namespaces":
        return segs[base + 2] if len(segs) > base + 2 else "namespaces"
    return segs[base]


class K8sApi:
    """Minimal stdlib HTTP client for the API server.

    qps/burst (reference: options.go:40-46, client-go DefaultQPS=5 /
    DefaultBurst=10) apply a client-side token-bucket throttle to every
    request, watches included; qps <= 0 disables throttling.

    Transient failures retry with capped jittered exponential backoff
    (client-go's retry.OnError shape): 409 Conflict (not AlreadyExists —
    that one is a semantic answer), 5xx, and network/timeout errors, on
    unary requests only (watch streams have the informer's own recovery
    loop). `retries` bounds the EXTRA attempts; 0 disables. A real
    apiserver behind a flapping LB turns every controller write into a
    coin flip without this; with it, a burst of 503s costs milliseconds
    instead of a dropped status transition."""

    def __init__(
        self,
        base_url: str,
        token: str | None = None,
        ca_file: str | None = None,
        insecure: bool = False,
        timeout: float = 30.0,
        qps: float = 0.0,
        burst: int = 10,
        retries: int = 3,
        retry_base: float = 0.05,
        retry_cap: float = 1.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.retries = max(0, int(retries))
        self.retry_base = retry_base
        self.retry_cap = retry_cap
        self._limiter = _TokenBucket(qps, burst) if qps > 0 else None
        if base_url.startswith("https"):
            if insecure:
                ctx = ssl._create_unverified_context()  # noqa: S323 — opt-in
            else:
                ctx = ssl.create_default_context(cafile=ca_file)
            self._ctx: ssl.SSLContext | None = ctx
        else:
            self._ctx = None

    @classmethod
    def in_cluster(cls, qps: float = 0.0, burst: int = 10) -> "K8sApi":
        """Service-account config, like rest.InClusterConfig (server.go:99)."""
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return cls(f"https://{host}:{port}", token=token,
                   ca_file=f"{SA_DIR}/ca.crt", qps=qps, burst=burst)

    def _open(self, method: str, path: str, body: dict | None,
              params: dict | None, timeout: float | None = None,
              content_type: str = "application/json"):
        if self._limiter is not None:
            self._limiter.acquire()
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self.timeout, context=self._ctx
            )
        except urllib.error.HTTPError as e:
            raise self._map_error(e) from None

    @staticmethod
    def _map_error(e: urllib.error.HTTPError) -> ApiError:
        try:
            payload = json.loads(e.read().decode() or "{}")
        except ValueError:
            payload = {}
        reason = payload.get("reason", "")
        msg = payload.get("message", str(e))
        if e.code == 404:
            err: ApiError = NotFoundError(msg)
        elif e.code == 409:
            if reason == "AlreadyExists":
                err = AlreadyExistsError(msg)
            else:
                err = ConflictError(msg)
        elif e.code == 410:
            err = GoneError(msg)
        else:
            err = ApiError(f"HTTP {e.code}: {msg}")
        err.code = e.code  # retry classification reads the raw status
        return err

    @staticmethod
    def _retryable(err: Exception) -> bool:
        """Transient per client-go's shouldRetry: raw 409 write contention
        (a re-read-and-retry upstream still benefits from the wait) and
        any 5xx. AlreadyExists/404/410 are semantic answers, never retried
        (410 drives the informer's relist protocol)."""
        if isinstance(err, (AlreadyExistsError, NotFoundError, GoneError)):
            return False
        if isinstance(err, ConflictError):
            return True
        code = getattr(err, "code", None)
        return code is not None and 500 <= code <= 599

    def _retry_sleep(self, attempt: int) -> None:
        import random

        delay = min(self.retry_cap, self.retry_base * (2 ** attempt))
        # Full-ish jitter (0.5x-1x): retries from many controller workers
        # must not re-converge on the struggling server in lockstep.
        time.sleep(delay * (0.5 + random.random() * 0.5))

    def _do(self, method: str, path: str, body: dict | None,
            params: dict | None, timeout: float | None = None,
            content_type: str = "application/json") -> str:
        """Open AND read one unary request under the retry policy (the
        read is inside the loop: a connection dropped mid-body is the same
        transient as one dropped pre-status)."""
        kind = _path_resource(path)
        attempt = 0
        while True:
            # Per attempt, not per call: a retry IS another request the
            # apiserver served — the load this family exists to budget.
            metrics.apiserver_requests.labels(verb=method, kind=kind).inc()
            try:
                with self._open(method, path, body, params, timeout=timeout,
                                content_type=content_type) as r:
                    return r.read().decode(errors="replace")
            except ApiError as e:
                if attempt >= self.retries or not self._retryable(e):
                    raise
            except (urllib.error.URLError, TimeoutError, OSError,
                    http.client.HTTPException):
                # DNS/conn-reset/timeout — and HTTPException for the
                # mid-body drops (IncompleteRead is NOT an OSError: a
                # server closing cleanly before Content-Length bytes
                # arrive raises it from r.read()).
                if attempt >= self.retries:
                    raise
            self._retry_sleep(attempt)
            attempt += 1

    def request(self, method: str, path: str, body: dict | None = None,
                params: dict | None = None,
                timeout: float | None = None) -> dict:
        text = self._do(method, path, body, params, timeout=timeout)
        return json.loads(text) if text else {}

    def merge_patch(self, path: str, patch: dict,
                    timeout: float | None = None) -> dict:
        """RFC 7386 JSON merge-patch (Content-Type
        application/merge-patch+json): provided keys replace, objects merge
        recursively, explicit null deletes. Unlike PUT there is no
        resourceVersion precondition, so two writers owning disjoint fields
        (controller: job status; kubelet: pod status) never conflict —
        the reason the reference client patches pods
        (pkg/control/pod_control.go:104-126 PatchPod)."""
        text = self._do("PATCH", path, patch, None, timeout=timeout,
                        content_type="application/merge-patch+json")
        return json.loads(text) if text else {}

    def request_text(self, method: str, path: str,
                     params: dict | None = None) -> str:
        """Raw-text request for non-JSON subresources (pod logs)."""
        return self._do(method, path, None, params)

    def stream(self, path: str, params: dict | None = None,
               on_response: Callable | None = None):
        """Yield JSON objects from a watch stream (one per line).
        on_response receives the live response object so the caller can
        close it from another thread (the informer stop path)."""
        r = self._open("GET", path, None, params, timeout=3600.0)
        if on_response is not None:
            on_response(r)
        try:
            for line in r:
                line = line.strip()
                if line:
                    yield json.loads(line)
        finally:
            r.close()


# ---------------------------------------------------------------------------
# Informer: list + watch -> substrate handler events
# ---------------------------------------------------------------------------


class _Informer(threading.Thread):
    def __init__(self, cluster: "K8sCluster", kind: str,
                 selector: dict[str, str] | None = None):
        super().__init__(daemon=True, name=f"informer-{kind}")
        self.cluster = cluster
        self.kind = kind
        # Reference parity: pod/service informers are label-filtered to the
        # operator's own objects — an unfiltered watch on a shared cluster
        # would list/decode the world on every relist.
        self.selector = selector
        self._stop = threading.Event()
        self._resp = None  # live watch response, closed by stop()
        self._watch_rv = 0  # resume point: last event/bookmark rv seen
        self._cache: dict[tuple[str, str], Any] = {}
        self.synced = threading.Event()
        self._log = FieldLogger({"component": f"informer-{kind}"})

    def stop(self) -> None:
        self._stop.set()
        resp = self._resp
        if resp is not None:
            # resp.close() would deadlock on the BufferedReader lock held by
            # the blocked reader thread; socket.shutdown is thread-safe and
            # unblocks the read with EOF.
            try:
                import socket as _socket

                sock = getattr(getattr(resp, "fp", None), "raw", None)
                sock = getattr(sock, "_sock", None)
                if sock is not None:
                    sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    def _params(self, extra: dict | None = None) -> dict | None:
        params = dict(extra or {})
        if self.selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(self.selector.items())
            )
        return params or None

    def run(self) -> None:
        log = self._log
        backoff = 0.2
        # client-go reflector semantics: relist only when forced (first run,
        # 410 Gone, or decode trouble); plain transport breaks RESUME the
        # watch from the last event/bookmark rv. Bookmarks keep that resume
        # point fresh across idle stretches.
        need_relist = True
        watch_rv = 0
        while not self._stop.is_set():
            started = time.monotonic()
            try:
                if need_relist:
                    watch_rv = self._relist()
                    self.synced.set()
                    need_relist = False
                self._watch_rv = watch_rv
                for ev in self.cluster.api.stream(
                    self.cluster.list_path(self.kind),
                    self._params({"watch": "true",
                                  "resourceVersion": str(watch_rv),
                                  "allowWatchBookmarks": "true"}),
                    on_response=lambda r: setattr(self, "_resp", r),
                ):
                    if self._stop.is_set():
                        return
                    self._dispatch(ev)
                    watch_rv = self._watch_rv
            except GoneError as e:
                if self._stop.is_set():
                    return
                # 410 Gone: history compacted past our rv — full relist,
                # but through the SAME backoff as other failures: a server
                # compacting faster than our LIST->WATCH roundtrip would
                # otherwise be hammered with full lists in a tight loop.
                need_relist = True
                if time.monotonic() - started > 10.0:
                    backoff = 0.2
                log.info("watch expired (relist in %.1fs): %s", backoff, e)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)
            # Broad catch: the daemon informer is the only event source for
            # its kind — any escaped decode/transport error (KeyError from a
            # malformed object included) must recover, never kill the thread.
            except Exception as e:  # noqa: BLE001
                if self._stop.is_set():
                    return
                # A decode/KeyError mid-dispatch may have dropped an event:
                # resync the world. (A clean resume is only safe when the
                # stream itself broke, which surfaces as ApiError/OSError.)
                if not isinstance(e, (ApiError, OSError)):
                    need_relist = True
                # Reset backoff only after a healthy stretch: a server whose
                # LIST succeeds but WATCH immediately fails would otherwise
                # hammer the server in a tight loop forever.
                if time.monotonic() - started > 10.0:
                    backoff = 0.2
                log.info("watch error (retry in %.1fs, relist=%s): %s",
                         backoff, need_relist, e)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 5.0)
            finally:
                self._resp = None

    def _decode_item(self, item: dict):
        """Decode one object, or skip it (reference parity: the unstructured
        informer tolerates CRs the typed codec would choke on, informer.go:82;
        one undecodable object must not stall every object of the kind)."""
        try:
            return self.cluster.decode(self.kind, item)
        except Exception as e:  # noqa: BLE001 — skip, don't poison the stream
            meta = item.get("metadata") or {}
            self._log.error(
                "skipping undecodable %s %s/%s: %r", self.kind,
                meta.get("namespace", "?"), meta.get("name", "?"), e,
            )
            return None

    def _relist(self) -> int:
        data = self.cluster.api.request(
            "GET", self.cluster.list_path(self.kind), params=self._params()
        )
        rv = data.get("metadata", {}).get("resourceVersion", 0)
        seen: set[tuple[str, str]] = set()
        for item in data.get("items", []):
            obj = self._decode_item(item)
            if obj is None:
                # Present-but-undecodable: keep any cached copy and keep its
                # key in `seen` so the sweep below doesn't fire a spurious
                # delete for an object that still exists on the server.
                meta = item.get("metadata") or {}
                seen.add((meta.get("namespace", "default"),
                          meta.get("name", "")))
                continue
            key = (obj.namespace, obj.name)
            seen.add(key)
            old = self._cache.get(key)
            self._cache[key] = obj
            if old is None:
                self.cluster._fire(self.kind, "add", obj)
            elif old.metadata.resource_version != obj.metadata.resource_version:
                self.cluster._fire(self.kind, "update", obj, old=old)
        for key in list(self._cache):
            if key not in seen:
                self.cluster._fire(self.kind, "delete", self._cache.pop(key))
        try:
            return int(rv)
        except (TypeError, ValueError):
            return 0

    def _dispatch(self, ev: dict) -> None:
        etype = ev.get("type")
        if etype == "ERROR":
            # The payload is a Status object, not a resource: never feed it
            # through the codecs. 410 forces a relist; anything else breaks
            # the stream for a resumed watch.
            status = ev.get("object") or {}
            if status.get("code") == 410:
                raise GoneError(f"watch ERROR event: {status!r}")
            raise ApiError(f"watch ERROR event: {status!r}")
        raw = ev.get("object") or {}
        if etype == "BOOKMARK":
            # Bookmark: no object payload beyond metadata.resourceVersion —
            # just advance the resume point (client-go reflector parity).
            try:
                self._watch_rv = int(
                    (raw.get("metadata") or {}).get("resourceVersion"))
            except (TypeError, ValueError):
                pass
            return
        # Every delivered event advances the resume point (undecodable
        # objects included — their event was still consumed from history).
        try:
            self._watch_rv = int(
                (raw.get("metadata") or {}).get("resourceVersion"))
        except (TypeError, ValueError):
            pass
        if etype == "DELETED":
            # The tombstone may carry undecodable last state; deletion only
            # needs the key — fall back to the cached copy so the delete
            # handler still fires and the cache can't leak the object.
            meta = raw.get("metadata") or {}
            key = (meta.get("namespace", "default"), meta.get("name", ""))
            cached = self._cache.pop(key, None)
            obj = self._decode_item(raw) or cached
            if obj is not None:
                self.cluster._fire(self.kind, "delete", obj)
            return
        obj = self._decode_item(raw)
        if obj is None:
            return
        key = (obj.namespace, obj.name)
        if etype == "ADDED":
            self._cache[key] = obj
            self.cluster._fire(self.kind, "add", obj)
        elif etype == "MODIFIED":
            old = self._cache.get(key)
            self._cache[key] = obj
            self.cluster._fire(self.kind, "update", obj, old=old)


# ---------------------------------------------------------------------------
# The adapter
# ---------------------------------------------------------------------------


class K8sCluster:
    """Cluster-substrate implementation over a K8s API server.

    Same method surface as InMemoryCluster (the controller cannot tell them
    apart); reads go to the API server directly (the informer cache backs
    only handler delivery), writes are plain REST calls.
    """

    _CODECS = {
        KIND_JOB: (job_to_k8s, job_from_k8s),
        KIND_INFSVC: (infsvc_to_k8s, infsvc_from_k8s),
        KIND_POD: (pod_to_k8s, pod_from_k8s),
        KIND_SERVICE: (service_to_k8s, service_from_k8s),
        KIND_PODGROUP: (podgroup_to_k8s, podgroup_from_k8s),
    }

    def __init__(self, api: K8sApi, namespace: str | None = None,
                 lists_from_cache: bool = False):
        self.api = api
        self.namespace = namespace  # None = all namespaces
        self._handlers: dict[tuple[str, str], list[Callable]] = {}
        self._informers: list[_Informer] = []
        self._lock = threading.Lock()
        # client-go lister semantics (fleet scale): serve pod/service
        # LISTs from the synced informer cache instead of a fresh
        # apiserver round-trip per reconcile. The controller's
        # expectations machinery exists precisely to absorb the cache's
        # bounded staleness (a just-created pod not yet delivered), and
        # every real operator reads through listers for this reason —
        # with thousands of jobs, two HTTP lists per sync is the
        # dominant apiserver load. Jobs stay read-through: status
        # latches (gang roll / preemption drains) must read their own
        # writes. Default off: bit-for-bit the old behavior.
        self.lists_from_cache = lists_from_cache

    # ------------------------------------------------------------- paths

    _RESOURCES = {KIND_POD: "pods", KIND_SERVICE: "services"}

    def _ns_path(self, kind: str, namespace: str) -> str:
        if kind == KIND_JOB:
            return (f"/apis/{TrainJob.API_VERSION}/namespaces/{namespace}/"
                    f"{TrainJob.PLURAL}")
        if kind == KIND_INFSVC:
            from tf_operator_tpu.api.types import InferenceService

            return (f"/apis/{InferenceService.API_VERSION}/namespaces/"
                    f"{namespace}/{InferenceService.PLURAL}")
        if kind == KIND_PODGROUP:
            return f"/apis/{PODGROUP_API}/namespaces/{namespace}/podgroups"
        return f"/api/v1/namespaces/{namespace}/{self._RESOURCES[kind]}"

    def list_path(self, kind: str) -> str:
        """Cluster- or namespace-scoped list path for informers."""
        if self.namespace:
            return self._ns_path(kind, self.namespace)
        if kind == KIND_JOB:
            return f"/apis/{TrainJob.API_VERSION}/{TrainJob.PLURAL}"
        if kind == KIND_INFSVC:
            from tf_operator_tpu.api.types import InferenceService

            return (f"/apis/{InferenceService.API_VERSION}/"
                    f"{InferenceService.PLURAL}")
        if kind == KIND_PODGROUP:
            return f"/apis/{PODGROUP_API}/podgroups"
        return f"/api/v1/{self._RESOURCES[kind]}"

    def decode(self, kind: str, d: dict):
        return self._CODECS[kind][1](d)

    def _encode(self, kind: str, obj) -> dict:
        return self._CODECS[kind][0](obj)

    # ---------------------------------------------------------- handlers

    def on_add(self, kind: str, fn: Callable) -> None:
        self._handlers.setdefault((kind, "add"), []).append(fn)

    def on_update(self, kind: str, fn: Callable) -> None:
        self._handlers.setdefault((kind, "update"), []).append(fn)

    def on_delete(self, kind: str, fn: Callable) -> None:
        self._handlers.setdefault((kind, "delete"), []).append(fn)

    def _fire(self, kind: str, event: str, obj, old=None) -> None:
        for fn in self._handlers.get((kind, event), []):
            try:
                if event == "update":
                    fn(old if old is not None else obj, obj)
                else:
                    fn(obj)
            except Exception as e:  # noqa: BLE001 — handler bugs must not kill informers
                import traceback

                FieldLogger({"component": "k8s-informer"}).error(
                    "handler error for %s %s: %s\n%s", kind, event, e,
                    traceback.format_exc(),
                )

    # ------------------------------------------------------ informer mgmt

    def start(self, kinds: tuple[str, ...] = (
            KIND_JOB, KIND_INFSVC, KIND_POD, KIND_SERVICE)) -> None:
        from tf_operator_tpu.core.controller import LABEL_GROUP_NAME

        own = {LABEL_GROUP_NAME: TrainJob.API_GROUP}
        for kind in kinds:
            # Owner kinds (jobs, inference services) are unlabeled; the
            # child kinds filter to our group's objects.
            selector = None if kind in (KIND_JOB, KIND_INFSVC) else own
            inf = _Informer(self, kind, selector=selector)
            self._informers.append(inf)
            inf.start()

    def wait_synced(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        for inf in self._informers:
            if not inf.synced.wait(max(0.0, deadline - time.monotonic())):
                return False
        return True

    def stop(self) -> None:
        for inf in self._informers:
            inf.stop()

    # --------------------------------------------------------- generic CRUD

    def _create(self, kind: str, obj):
        d = self.api.request(
            "POST", self._ns_path(kind, obj.namespace), self._encode(kind, obj)
        )
        return self.decode(kind, d)

    def _get(self, kind: str, namespace: str, name: str):
        d = self.api.request("GET", f"{self._ns_path(kind, namespace)}/{name}")
        return self.decode(kind, d)

    def _try_get(self, kind: str, namespace: str, name: str):
        try:
            return self._get(kind, namespace, name)
        except NotFoundError:
            return None

    def _update(self, kind: str, obj, subresource: str = ""):
        path = f"{self._ns_path(kind, obj.namespace)}/{obj.name}"
        if subresource:
            path += f"/{subresource}"
        d = self.api.request("PUT", path, self._encode(kind, obj))
        return self.decode(kind, d)

    def _patch(self, kind: str, namespace: str, name: str, patch: dict,
               subresource: str = ""):
        path = f"{self._ns_path(kind, namespace)}/{name}"
        if subresource:
            path += f"/{subresource}"
        d = self.api.merge_patch(path, patch)
        return self.decode(kind, d)

    def _diffed_status_patch(self, kind: str, obj, status_diff: dict,
                             base, expected_rv):
        """Merge-patches carrying only what this sync changed (round 17,
        amended by its review). Status ALWAYS ships via the /status
        subresource: both CRDs enable the subresource, and a real
        apiserver ignores the status stanza of a main-resource write —
        a combined patch would silently drop the status half (terminal
        conditions, drain latches) on a real cluster. Annotations
        changed -> ONE extra main-resource patch carrying just the
        annotations (both stanzas are controller-owned, so each lane
        stays conflict-free against spec editors). The common
        status-only sync is still exactly one request; nothing changed
        -> NO request at all and the caller's working copy is returned
        as-is. With `expected_rv` each patch carries the observed (or
        just-written) resourceVersion — the server 409s a stale
        observation instead of merging it."""
        ann_diff = _wire_diff(dict(obj.metadata.annotations),
                              dict(base.metadata.annotations))
        if not status_diff and not ann_diff:
            return obj
        out = obj
        # Wire form is a string (see _meta_to_dict); the server compares
        # it verbatim against what it stored.
        rv = str(expected_rv) if expected_rv is not None else None
        if status_diff:
            patch: dict = {"status": status_diff}
            if rv is not None:
                patch["metadata"] = {"resourceVersion": rv}
            out = self._patch(kind, obj.namespace, obj.name, patch,
                              subresource="status")
            # The status write bumped the rv; fence the annotations
            # patch against the version we just wrote, not the stale
            # pre-write observation (which would always 409).
            if rv is not None:
                rv = str(out.metadata.resource_version)
        if ann_diff:
            meta: dict = {"annotations": ann_diff}
            if rv is not None:
                meta["resourceVersion"] = rv
            out = self._patch(kind, obj.namespace, obj.name,
                              {"metadata": meta})
        return out

    def _delete(self, kind: str, namespace: str, name: str):
        d = self.api.request(
            "DELETE", f"{self._ns_path(kind, namespace)}/{name}"
        )
        return self.decode(kind, d) if d.get("kind") not in (None, "Status") else None

    def _synced_informer(self, kind: str):
        return next((i for i in self._informers
                     if i.kind == kind and i.synced.is_set()), None)

    def _cache_list(self, kind: str, namespace: str | None,
                    selector: dict | None):
        """Lister-style read from the informer cache; None when the kind
        has no synced informer (callers fall back to HTTP).

        Round 17: jobs are no longer excluded. They used to stay
        read-through because status latches (gang roll / preemption
        drains) need read-your-writes — now (a) every status flush from
        a cache-served sync carries the observed resourceVersion as a
        fence, so a stale read can only cost a 409 + requeue, never a
        blind overwrite of a newer status (core/status_writer.py); and
        (b) the fence alone cannot undo side effects taken BEFORE the
        flush, so the controller re-verifies any observed destructive
        latch with a read-through GET and flushes latch writes before
        acting on them (trainjob_controller.sync_job / the tick
        callers)."""
        inf = self._synced_informer(kind)
        if inf is None:
            return None
        for _ in range(8):
            try:
                objs = list(inf._cache.values())
                break
            except RuntimeError:  # cache resized mid-iteration: retry
                continue
        else:
            return None
        out = []
        for o in objs:
            if namespace and o.namespace != namespace:
                continue
            if selector and any(
                    o.metadata.labels.get(k) != v
                    for k, v in selector.items()):
                continue
            # Deep copies: reconcile mutates listed objects (claim/adopt)
            # and must never write into the shared cache.
            out.append(copy.deepcopy(o))
        return out

    def _cache_get(self, kind: str, namespace: str, name: str):
        """Single-object lister read (round 17): the synced informer's
        copy, deep-copied because reconcile mutates what it reads. None
        falls back to read-through — including when the cache simply
        does not hold the key, so a just-created object racing its watch
        delivery costs one GET instead of a spurious not-found."""
        if not self.lists_from_cache:
            return None
        inf = self._synced_informer(kind)
        if inf is None:
            return None
        obj = inf._cache.get((namespace, name))
        return copy.deepcopy(obj) if obj is not None else None

    def snapshot_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        """Read-only lister snapshot of every job — NO deep copies and,
        with a synced informer, NO apiserver round-trip. For scans that
        only inspect (resync enqueue, slice-waiter kicks): at 10k jobs a
        full HTTP LIST is megabytes of wire and decode per resync wave.
        Callers must not mutate the returned objects."""
        inf = self._synced_informer(KIND_JOB)
        if inf is None:
            return self._list(KIND_JOB, namespace, None)
        for _ in range(8):
            try:
                objs = list(inf._cache.values())
                break
            except RuntimeError:  # cache resized mid-iteration: retry
                continue
        else:
            return self._list(KIND_JOB, namespace, None)
        if namespace is None:
            return objs
        return [o for o in objs if o.namespace == namespace]

    def snapshot_infsvcs(self, namespace: str | None = None) -> list:
        """Read-only lister snapshot of inference services (see
        snapshot_jobs)."""
        inf = self._synced_informer(KIND_INFSVC)
        if inf is None:
            return self._list(KIND_INFSVC, namespace, None)
        for _ in range(8):
            try:
                objs = list(inf._cache.values())
                break
            except RuntimeError:
                continue
        else:
            return self._list(KIND_INFSVC, namespace, None)
        if namespace is None:
            return objs
        return [o for o in objs if o.namespace == namespace]

    def _list(self, kind: str, namespace: str | None, selector: dict | None):
        if self.lists_from_cache:
            cached = self._cache_list(kind, namespace, selector)
            if cached is not None:
                return cached
        if namespace:
            path = self._ns_path(kind, namespace)
        else:
            path = self.list_path(kind)
        params = {}
        if selector:
            params["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(selector.items())
            )
        data = self.api.request("GET", path, params=params or None)
        return [self.decode(kind, item) for item in data.get("items", [])]

    # ----------------------------------------------------------- jobs

    def create_job(self, job: TrainJob) -> TrainJob:
        return self._create(KIND_JOB, job)

    def get_job(self, namespace: str, name: str) -> TrainJob:
        return self._get(KIND_JOB, namespace, name)

    def try_get_job(self, namespace: str, name: str, *,
                    read_through: bool = False) -> TrainJob | None:
        """`read_through=True` bypasses the lister cache for this one
        read (round-17 review): destructive status latches (preemption
        drain, gang roll) drive pod deletes and scheduler requeues in
        the SAME sync that observes them — those need read-your-writes,
        which the cache cannot promise and the flush-time rv fence
        cannot retroactively undo."""
        if not read_through:
            cached = self._cache_get(KIND_JOB, namespace, name)
            if cached is not None:
                return cached
        return self._try_get(KIND_JOB, namespace, name)

    def update_job(self, job: TrainJob) -> TrainJob:
        return self._update(KIND_JOB, job)

    def update_job_status(self, job: TrainJob, *, expected_rv=None,
                          base=None) -> TrainJob:
        """Status + bookkeeping-annotation write via JSON merge-patch (ref
        UpdateStatus, k8sutil/client.go:85; PATCH per pod_control.go:104).

        The controller owns the whole status and its own annotations, so a
        merge-patch is conflict-free against concurrent spec editors
        (kubectl, the dashboard) — a whole-object PUT here would fight them
        on resourceVersion (VERDICT r3 missing #2). The status dict always
        carries every key the engine owns; None values become explicit
        merge-patch nulls, which delete — matching PUT's omitempty.

        Round 17: with `base` (the object as the caller OBSERVED it), the
        patch ships only the top-level status keys that actually changed
        plus the changed annotations, as ONE request — byte-identical wire
        forms issue ZERO requests. With `expected_rv` the patch carries
        the observed resourceVersion as a precondition (409 on staleness;
        the lister-snapshot fence). Without `base` the legacy full-form
        two-patch write is preserved — that path stays rv-free so it can
        never fight a concurrent spec editor (test_k8s pins this).
        """
        if base is None:
            if job.metadata.annotations:
                try:
                    self._patch(
                        KIND_JOB, job.namespace, job.name,
                        {"metadata": {
                            "annotations": dict(job.metadata.annotations)}},
                    )
                except NotFoundError:
                    pass  # deleted underneath us: status write will 404 too
            return self._patch(
                KIND_JOB, job.namespace, job.name,
                {"status": job_status_to_dict(job.status)},
                subresource="status",
            )
        return self._diffed_status_patch(
            KIND_JOB, job,
            _wire_diff(job_status_to_dict(job.status),
                       job_status_to_dict(base.status)),
            base, expected_rv)

    def delete_job(self, namespace: str, name: str):
        return self._delete(KIND_JOB, namespace, name)

    def list_jobs(self, namespace: str | None = None) -> list[TrainJob]:
        return self._list(KIND_JOB, namespace, None)

    # ----------------------------------------- inference services (serve/)

    def create_infsvc(self, svc):
        return self._create(KIND_INFSVC, svc)

    def get_infsvc(self, namespace: str, name: str):
        return self._get(KIND_INFSVC, namespace, name)

    def try_get_infsvc(self, namespace: str, name: str):
        cached = self._cache_get(KIND_INFSVC, namespace, name)
        if cached is not None:
            return cached
        return self._try_get(KIND_INFSVC, namespace, name)

    def update_infsvc(self, svc):
        return self._update(KIND_INFSVC, svc)

    def update_infsvc_status(self, svc, *, expected_rv=None, base=None):
        """Same merge-patch discipline as update_job_status — including
        the round-17 diffed single-patch / no-op-skip / rv-fence path:
        the controller owns status + its annotations; spec editors keep
        their resourceVersion lane."""
        if base is None:
            if svc.metadata.annotations:
                try:
                    self._patch(
                        KIND_INFSVC, svc.namespace, svc.name,
                        {"metadata": {"annotations":
                                      dict(svc.metadata.annotations)}},
                    )
                except NotFoundError:
                    pass
            return self._patch(
                KIND_INFSVC, svc.namespace, svc.name,
                {"status": infsvc_status_to_dict(svc.status)},
                subresource="status",
            )
        return self._diffed_status_patch(
            KIND_INFSVC, svc,
            _wire_diff(infsvc_status_to_dict(svc.status),
                       infsvc_status_to_dict(base.status)),
            base, expected_rv)

    def delete_infsvc(self, namespace: str, name: str):
        return self._delete(KIND_INFSVC, namespace, name)

    def list_infsvcs(self, namespace: str | None = None) -> list:
        return self._list(KIND_INFSVC, namespace, None)

    # ----------------------------------------------------------- pods

    def create_pod(self, pod: Pod) -> Pod:
        return self._create(KIND_POD, pod)

    def get_pod(self, namespace: str, name: str) -> Pod:
        return self._get(KIND_POD, namespace, name)

    def try_get_pod(self, namespace: str, name: str) -> Pod | None:
        return self._try_get(KIND_POD, namespace, name)

    def update_pod(self, pod: Pod) -> Pod:
        """Metadata/spec write (controller adoption etc.) — status is the
        kubelet's resource; use update_pod_status for phase transitions."""
        return self._update(KIND_POD, pod)

    def update_pod_status(self, pod: Pod) -> Pod:
        """Kubelet-side write via JSON merge-patch: the runtime's updates
        carry metadata (the endpoint annotation) and status (phase
        transitions) — two patches on the fields the kubelet owns, so it
        never conflicts with the controller PUTting labels/ownerRefs on the
        same pod (the classic PUT-vs-kubelet fight, VERDICT r3 missing #2;
        ref pod_control.go:104-126 PatchPod)."""
        if pod.metadata.annotations:
            self._patch(
                KIND_POD, pod.namespace, pod.name,
                {"metadata": {"annotations": dict(pod.metadata.annotations)}},
            )
        return self._patch(
            KIND_POD, pod.namespace, pod.name,
            {"status": pod_to_k8s(pod)["status"]},
            subresource="status",
        )

    def delete_pod(self, namespace: str, name: str):
        return self._delete(KIND_POD, namespace, name)

    def list_pods(self, namespace: str | None = None,
                  selector: dict | None = None) -> list[Pod]:
        return self._list(KIND_POD, namespace, selector)

    def pod_logs(self, namespace: str, name: str,
                 container: str | None = None,
                 tail_lines: int | None = None) -> str:
        """Pod-log subresource — the dashboard's log view in --kube-api
        mode (ref dashboard/backend/handler/api_handler.go:237)."""
        params: dict[str, str] = {}
        if container:
            params["container"] = container
        if tail_lines:
            params["tailLines"] = str(tail_lines)
        return self.api.request_text(
            "GET", f"/api/v1/namespaces/{namespace}/pods/{name}/log",
            params=params or None,
        )

    # -------------------------------------------------------- services

    def create_service(self, svc: Service) -> Service:
        return self._create(KIND_SERVICE, svc)

    def get_service(self, namespace: str, name: str) -> Service:
        return self._get(KIND_SERVICE, namespace, name)

    def update_service(self, svc: Service) -> Service:
        return self._update(KIND_SERVICE, svc)

    def delete_service(self, namespace: str, name: str):
        return self._delete(KIND_SERVICE, namespace, name)

    def list_services(self, namespace: str | None = None,
                      selector: dict | None = None) -> list[Service]:
        return self._list(KIND_SERVICE, namespace, selector)

    # ------------------------------------------------------- pod groups

    def create_podgroup(self, pg: PodGroup) -> PodGroup:
        return self._create(KIND_PODGROUP, pg)

    def try_get_podgroup(self, namespace: str, name: str) -> PodGroup | None:
        return self._try_get(KIND_PODGROUP, namespace, name)

    def update_podgroup(self, pg: PodGroup) -> PodGroup:
        return self._update(KIND_PODGROUP, pg)

    def delete_podgroup(self, namespace: str, name: str):
        try:
            return self._delete(KIND_PODGROUP, namespace, name)
        except NotFoundError:
            return None

    def list_podgroups(self, namespace: str | None = None) -> list[PodGroup]:
        return self._list(KIND_PODGROUP, namespace, None)

    # ----------------------------------------------------------- events

    def record_event(self, kind: str, namespace: str, name: str,
                     etype: str, reason: str, message: str) -> None:
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name}.{int(time.time() * 1e6):x}",
                "namespace": namespace,
            },
            "involvedObject": {"kind": kind, "namespace": namespace, "name": name},
            "type": etype,
            "reason": reason,
            "message": message,
        }
        try:
            self.api.request(
                "POST", f"/api/v1/namespaces/{namespace}/events", body
            )
        except ApiError:
            pass  # events are best-effort, as in client-go recorders

    def events_for(self, kind: str, namespace: str, name: str) -> list[Event]:
        try:
            data = self.api.request(
                "GET", f"/api/v1/namespaces/{namespace}/events"
            )
        except ApiError:
            return []
        out = []
        for item in data.get("items", []):
            inv = item.get("involvedObject") or {}
            if inv.get("kind") == kind and inv.get("name") == name:
                out.append(
                    Event(kind, namespace, name, item.get("type", ""),
                          item.get("reason", ""), item.get("message", ""))
                )
        return out
