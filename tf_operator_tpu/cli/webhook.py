"""ValidatingAdmissionWebhook endpoint: semantic validation at admission.

The reference tolerated semantically-invalid CRs reaching the controller and
marked them Failed at reconcile (informer.go:34-123's unstructured-informer
workaround). This build's design stance (SURVEY §7) is validate-at-admission:
a structurally-valid-but-semantically-invalid CR (two chiefs, no `tensorflow`
container, negative replicas) is rejected before it is stored. On the in-repo
substrates that admission lives in `cli/server.py` and the fake apiserver's
schema check; THIS module is the missing real-cluster leg (VERDICT r3
missing #1): an `admission.k8s.io/v1 AdmissionReview` endpoint a real
apiserver calls through `manifests/webhook.yaml`, reusing the exact same
`api/validation.py` invariants (parity: validation.go:27-73).

Reconcile-time fallback stays: if no webhook is registered (or its
failurePolicy lets a CR through), `sync_job` still marks the job Failed
(trainjob_controller.py) — admission is the first line, not the only one.

Real clusters require webhooks to serve HTTPS; pass cert/key paths to enable
TLS. Plain HTTP is for the in-repo fake-apiserver substrate.
"""

from __future__ import annotations

import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tf_operator_tpu.api.validation import validate_job
from tf_operator_tpu.core.k8s import job_from_k8s


def review_response(review: dict, fleet=None) -> dict:
    """Pure request->response admission logic (unit-testable sans HTTP).

    Accepts an `AdmissionReview` dict; returns the AdmissionReview response
    envelope with `.response.allowed` and, on denial, a `.response.status`
    whose code is 400 (the code kubectl surfaces as the denial message).
    `fleet` (sched.FleetPolicy) additionally rejects unknown
    priorityClass names and zero-quota namespaces at admission.
    """
    req = review.get("request") or {}
    uid = req.get("uid", "")
    obj = req.get("object") or {}
    problems: list[str]
    if req.get("operation") in (None, "CREATE", "UPDATE"):
        try:
            problems = validate_job(job_from_k8s(obj), fleet=fleet)
        except Exception as exc:  # malformed beyond parsing: deny, not crash
            problems = [f"unparseable TrainJob: {exc}"]
    else:  # DELETE etc. carry no object to validate
        problems = []
    resp: dict = {"uid": uid, "allowed": not problems}
    if problems:
        resp["status"] = {"code": 400, "message": "; ".join(problems[:5])}
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": resp,
    }


class AdmissionWebhookServer:
    """Serves POST /validate. TLS when cert_file/key_file are given (real
    clusters require it); plain HTTP otherwise (in-repo substrate)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 cert_file: str | None = None, key_file: str | None = None,
                 fleet=None):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_POST(self):  # noqa: N802
                if self.path.split("?")[0] != "/validate":
                    self.send_error(404)
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    review = json.loads(self.rfile.read(n) or b"{}")
                    payload = review_response(review, fleet=fleet)
                except ValueError:
                    self.send_error(400, "bad AdmissionReview payload")
                    return
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        if cert_file:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert_file, key_file)
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True
            )
        self.port = self._server.server_port
        self.url = (f"{'https' if cert_file else 'http'}://{host}:"
                    f"{self.port}/validate")
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="admission-webhook",
        )

    def start(self) -> "AdmissionWebhookServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self) -> "AdmissionWebhookServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
