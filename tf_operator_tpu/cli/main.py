"""tpujob CLI — the operator binary and job client.

Capability parity with cmd/tf-operator.v1 (options.go:27-83, server.go:68-223)
re-targeted at the local substrate:

  tpujob run JOB.yaml          submit + execute locally, stream conditions
  tpujob validate JOB.yaml     defaulting + validation report
  tpujob operator [flags]      long-running operator: REST API on
                               --monitoring-port (default 8443, /metrics +
                               /healthz + dashboard API), leader election
                               (--enable-leader-election, file lock), gang
                               scheduling (--enable-gang-scheduling,
                               --gang-scheduler-name, --tpu-slices), worker
                               threads (--threadiness)
  tpujob get [NS [NAME]]       query a running operator's REST API
  tpujob submit JOB.yaml       submit to a running operator via REST
  tpujob timeline NAME         causal phase view of one job's lifecycle
                               from the operator's flight recorder
  tpujob version               version info (pkg/version parity)

Exit codes: run returns 0 on Succeeded, 1 on Failed.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import urllib.error
import urllib.request

from tf_operator_tpu.api import compat, validation
from tf_operator_tpu.utils.logging import FieldLogger


def _load_job(path: str):
    with open(path) as f:
        return compat.job_from_yaml(f.read())


def _manifest_kind(path: str) -> str:
    import yaml

    with open(path) as f:
        return (yaml.safe_load(f.read()) or {}).get("kind", "TrainJob")


def cmd_validate(args) -> int:
    if _manifest_kind(args.manifest) == "InferenceService":
        with open(args.manifest) as f:
            svc = compat.infsvc_from_yaml(f.read())
        problems = validation.validate_inference_service(svc)
        if problems:
            for p in problems:
                print(f"INVALID: {p}")
            return 1
        print(f"OK: InferenceService {svc.namespace}/{svc.name} is valid")
        print(f"  model: {svc.spec.model.checkpoint_dir or svc.spec.model.from_train_job}")
        print(f"  serving: batchMaxSize={svc.spec.serving.batch_max_size} "
              f"batchTimeoutMs={svc.spec.serving.batch_timeout_ms:g} "
              f"port={svc.spec.serving.port}")
        print(f"  autoscale: {svc.spec.autoscale.min_replicas}.."
              f"{svc.spec.autoscale.max_replicas} @ "
              f"{svc.spec.autoscale.target_inflight_per_replica:g} "
              f"inflight/replica")
        return 0
    job = _load_job(args.manifest)
    problems = validation.validate_job(job)
    if problems:
        for p in problems:
            print(f"INVALID: {p}")
        return 1
    print(f"OK: TrainJob {job.namespace}/{job.name} is valid")
    for rtype, spec in job.spec.replica_specs.items():
        print(f"  {rtype}: replicas={spec.replicas} restartPolicy={spec.restart_policy}")
    if job.spec.tpu:
        print(f"  tpu: topology={job.spec.tpu.topology}")
    if job.spec.mesh:
        print(f"  mesh: {job.spec.mesh.axes}")
    return 0


def cmd_run(args) -> int:
    from tf_operator_tpu.api.types import is_succeeded
    from tf_operator_tpu.gang.podgroup import SliceAllocator
    from tf_operator_tpu.runtime.session import LocalSession

    job = _load_job(args.manifest)
    problems = validation.validate_job(job)
    if problems:
        for p in problems:
            print(f"INVALID: {p}", file=sys.stderr)
        return 1

    allocator = SliceAllocator.of(*args.tpu_slices) if args.tpu_slices else None
    session = LocalSession(
        enable_gang=bool(args.tpu_slices),
        slice_allocator=allocator,
        log_dir=args.log_dir,
    )
    log = FieldLogger({"job": job.key()})
    try:
        session.submit(job)
        log.info("submitted; waiting for completion")
        seen: set[str] = set()

        import time

        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            cur = session.get(job.namespace, job.name)
            if cur is None:
                print("DELETED: job was removed before completion", file=sys.stderr)
                return 2
            for c in cur.status.conditions:
                tag = f"{c.type}:{c.status}:{c.reason}"
                if c.status and tag not in seen:
                    seen.add(tag)
                    print(f"[{c.type}] {c.message}")
            if cur.status.completion_time is not None:
                ok = is_succeeded(cur.status)
                print("SUCCEEDED" if ok else "FAILED")
                return 0 if ok else 1
            time.sleep(0.2)
        print("TIMEOUT", file=sys.stderr)
        return 2
    finally:
        session.close()


def cmd_operator(args) -> int:
    from tf_operator_tpu.cli.server import ApiServer
    from tf_operator_tpu.core.cluster import InMemoryCluster
    from tf_operator_tpu.core.trainjob_controller import TrainJobController
    from tf_operator_tpu.gang.podgroup import SliceAllocator
    from tf_operator_tpu.runtime.local import LocalProcessRuntime
    from tf_operator_tpu.utils.leader import LeaderElector

    log = FieldLogger({"component": "operator"})
    # Flight recorder sizing: the journal is ON by default (bounded ring
    # per job, O(1) appends — docs/monitoring.md "Flight recorder").
    from tf_operator_tpu.telemetry import journal as journal_lib

    journal_lib.configure(
        enabled=not args.no_journal,
        per_job_capacity=args.journal_events,
        max_jobs=args.journal_jobs,
    )
    # Operator-side tracing is opt-in (--trace PATH): spans around every
    # reconcile pass, scheduler decide, and status flush land in a
    # Perfetto/chrome://tracing-loadable Chrome trace on shutdown.
    if args.trace:
        from tf_operator_tpu.telemetry import tracer as tracer_lib

        tracer_lib.configure(enabled=True)
    # Fleet scheduling policy (sched/): priority classes, per-namespace
    # quotas, weighted queues, preemption cooldown. With --tpu-slices the
    # scheduler arbitrates the fleet; without slices the policy still
    # drives admission validation.
    fleet_policy = None
    if args.fleet_config:
        from tf_operator_tpu.sched.policy import fleet_policy_from_yaml

        with open(args.fleet_config) as f:
            fleet_policy = fleet_policy_from_yaml(f.read())
        log.info("fleet policy loaded from %s (%d priority classes, "
                 "%d quotas, %d queues)", args.fleet_config,
                 len(fleet_policy.priority_classes),
                 len(fleet_policy.quotas), len(fleet_policy.queues))
    # Substrate: a K8s API server (real cluster deployment — pods run as
    # real cluster pods, kubelet feeds status back) or the in-memory
    # substrate with the local-process runtime (one-host deployment).
    on_k8s = bool(args.kube_api or args.in_cluster)
    if on_k8s:
        from tf_operator_tpu.core.k8s import K8sApi, K8sCluster

        qps = getattr(args, "kube_api_qps", 5.0)  # parser default
        burst = getattr(args, "kube_api_burst", 10)
        api_client = (
            K8sApi.in_cluster(qps=qps, burst=burst) if args.in_cluster
            else K8sApi(args.kube_api, token=args.kube_token,
                        insecure=args.kube_insecure, qps=qps, burst=burst)
        )
        cluster = K8sCluster(api_client, namespace=args.namespace or None)
    else:
        cluster = InMemoryCluster()
    allocator = SliceAllocator.of(*args.tpu_slices) if args.tpu_slices else None
    scheduler = None
    if allocator is not None:
        from tf_operator_tpu.sched import FleetScheduler

        scheduler = FleetScheduler(allocator, policy=fleet_policy)
        log.info("fleet scheduler arbitrating %d slice(s)",
                 len(allocator.slices))

    # Admission webhook serves on EVERY replica (stateless, no leadership
    # needed — a real cluster load-balances webhook calls across the
    # Service's endpoints). 0 = disabled.
    webhook_server = None
    if args.webhook_port:
        from tf_operator_tpu.cli.webhook import AdmissionWebhookServer

        webhook_server = AdmissionWebhookServer(
            port=args.webhook_port, host=args.webhook_bind,
            cert_file=args.webhook_cert, key_file=args.webhook_key,
            fleet=scheduler.policy if scheduler is not None else fleet_policy,
        ).start()
        log.info("admission webhook on %s", webhook_server.url)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    failed = threading.Event()  # startup failures must exit non-zero

    def lead() -> None:
        # Heartbeat source for the hang watchdog: the same log_dir the
        # local runtime injects TPUJOB_HEARTBEAT_FILE under. (On K8s the
        # pods' heartbeat files only exist where a shared log volume is
        # mounted; without one the watchdog simply never arms.)
        heartbeat_source = None
        if args.log_dir:
            from tf_operator_tpu.telemetry.collector import TelemetryCollector

            heartbeat_source = TelemetryCollector(args.log_dir)
        # Two workload kinds share one scheduler/allocator: the shared
        # router (core.controller.make_enqueue_router) dispatches
        # capacity kicks and preemption victims to whichever controller
        # owns the key (serve-replica claims carry the claim separator).
        from tf_operator_tpu.core.controller import make_enqueue_router
        from tf_operator_tpu.serve.controller import (
            InferenceServiceController,
        )

        train_controller_ref: list = []
        serve_controller_ref: list = []
        _route = make_enqueue_router(train_controller_ref,
                                     serve_controller_ref)

        controller = TrainJobController(
            cluster,
            enable_gang=args.enable_gang_scheduling,
            gang_scheduler_name=args.gang_scheduler_name,
            slice_allocator=allocator,
            heartbeat_source=heartbeat_source,
            scheduler=scheduler,
            queue_shards=args.queue_shards,
            fleet_policy=fleet_policy,
            enqueue_router=_route,
        )
        train_controller_ref.append(controller)
        serve_controller = InferenceServiceController(
            cluster,
            slice_allocator=allocator,
            scheduler=scheduler,
            heartbeat_source=heartbeat_source,
            fleet_policy=fleet_policy,
            enqueue_router=_route,
        )
        serve_controller_ref.append(serve_controller)
        runtime = None
        if on_k8s:
            cluster.start()
            if not cluster.wait_synced(60):
                log.error("informer caches never synced; exiting")
                failed.set()
                return
            log.info("K8s informers synced (%s)", args.kube_api or "in-cluster")
        else:
            runtime = LocalProcessRuntime(cluster, log_dir=args.log_dir)
            # Local runtime: the serve controller runs an in-process
            # front-end router per InferenceService, with backends
            # resolved through the runtime's port map (on K8s the
            # front-end is a readiness-probed Service/LB instead).
            from tf_operator_tpu.serve.router import (
                local_endpoint_resolver,
            )

            serve_controller.endpoint_resolver = (
                local_endpoint_resolver(runtime))
        # Leadership won and informers synced: hand the port from the
        # standby /healthz stub to the real ApiServer HERE (not at the top
        # of lead() — controller construction + informer sync can take tens
        # of seconds, and a probe gap that long would flip the just-promoted
        # leader to NotReady mid-rollout).
        if health_stub is not None:
            health_stub.shutdown()
            health_stub.server_close()
        # The API binds only on the leader: a hot standby must not collide on
        # the monitoring port while waiting for the lock. Default loopback —
        # the API is unauthenticated, so a routable bind is an explicit
        # opt-in (--bind), not a side effect of --in-cluster (probes and
        # kubectl port-forward both enter via the pod's loopback).
        api = ApiServer(cluster, port=args.monitoring_port, log_dir=args.log_dir,
                        runtime=runtime, bind=args.bind,
                        telemetry=heartbeat_source, scheduler=scheduler,
                        fleet=fleet_policy,
                        controllers=[controller, serve_controller])
        api.start()
        log.info("REST/metrics API on %s:%d", args.bind, api.port)
        controller.run(workers=args.threadiness)
        serve_controller.run(workers=1)
        log.info("controllers running (threadiness=%d)", args.threadiness)
        stop.wait()
        if runtime is not None:
            runtime.stop()
        controller.stop()
        serve_controller.stop()
        if on_k8s:
            cluster.stop()
        api.stop()
        if args.trace:
            from tf_operator_tpu.telemetry import tracer as tracer_lib

            n = tracer_lib.get_tracer().export(args.trace)
            log.info("chrome trace: %d event(s) written to %s",
                     n, args.trace)

    # Standby health stub (in-cluster only — pods have their own netns, so
    # no port collision; on a shared host two operators DO collide, which is
    # why the full API binds only on the leader). Without it a Deployment
    # rolling update deadlocks: the surge pod can never pass readiness while
    # the old leader holds the Lease. The stub serves /healthz until
    # leadership, then hands the port to the real ApiServer.
    health_stub = None
    if args.in_cluster and args.enable_leader_election:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Health(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):  # noqa: N802
                pass

            def do_GET(self):  # noqa: N802
                code = 200 if self.path == "/healthz" else 404
                body = b"standby\n" if code == 200 else b"not found\n"
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        health_stub = ThreadingHTTPServer(
            (args.bind, args.monitoring_port), _Health)
        health_stub.daemon_threads = True
        threading.Thread(target=health_stub.serve_forever, daemon=True,
                         name="standby-healthz").start()
        log.info("standby /healthz on %s:%d (awaiting leadership)",
                 args.bind, args.monitoring_port)

    if args.enable_leader_election:
        if on_k8s:
            # Cluster-grade: N operator replicas across nodes serialize on a
            # coordination.k8s.io/v1 Lease (ref server.go:157-182 semantics).
            from tf_operator_tpu.utils.leader import LeaseElector

            clean = LeaseElector(
                api_client,
                namespace=args.namespace or "default",
                lease_duration=args.lease_duration,
                renew_period=args.lease_renew_period,
                retry_period=args.lease_retry_period,
                renew_deadline=args.lease_renew_deadline,
            ).run_or_die(lead, stop)
            if not clean:
                return 1  # lease lost: exit so the pod restarts as a standby
        else:
            LeaderElector(args.lock_file).run_or_die(lead, stop)
    else:
        lead()
    if webhook_server is not None:
        webhook_server.stop()
    return 1 if failed.is_set() else 0


def cmd_kubelet(args) -> int:
    """Node agent: run this node's share of pods from the API server as
    local processes (the kubelet role in SURVEY.md §3.3's 'kubelet starts
    the tensorflow container' step). With this running, `--kube-api` mode
    is a complete single-node cluster: operator reconciles CRs into pods,
    the agent executes them and feeds status back."""
    from tf_operator_tpu.core.cluster import KIND_POD
    from tf_operator_tpu.core.k8s import K8sApi, K8sCluster
    from tf_operator_tpu.runtime.local import LocalProcessRuntime

    log = FieldLogger({"component": "kubelet"})
    if not args.kube_api and not args.in_cluster:
        print("error: kubelet requires --kube-api URL or --in-cluster",
              file=sys.stderr)
        return 2
    api_client = (
        K8sApi.in_cluster() if args.in_cluster
        else K8sApi(args.kube_api, token=args.kube_token,
                    insecure=args.kube_insecure)
    )
    cluster = K8sCluster(api_client, namespace=args.namespace or None)
    runtime = LocalProcessRuntime(
        cluster, log_dir=args.log_dir,
        external_scheduler=args.external_scheduler,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    cluster.start((KIND_POD,))
    if not cluster.wait_synced(60):
        log.error("pod informer never synced; exiting")
        return 1
    log.info("node agent running against %s", args.kube_api or "in-cluster")
    stop.wait()
    runtime.stop()
    cluster.stop()
    return 0


def _api_get(server: str, path: str) -> dict:
    with urllib.request.urlopen(f"http://{server}{path}", timeout=10) as r:
        return json.loads(r.read())


def cmd_get(args) -> int:
    path = "/api/trainjobs"
    if args.namespace:
        path += f"/{args.namespace}"
        if args.name:
            path += f"/{args.name}"
    data = _api_get(args.server, path)
    print(json.dumps(data, indent=2, default=str))
    return 0


def render_timeline(data: dict, *, show_events: bool = True) -> str:
    """Human rendering of one job's flight-recorder timeline (the
    /api/trainjobs/{ns}/{name}/timeline payload): the causal phase
    breakdown first, then the raw event log, then whatever the trainer
    telemetry collector knows about the same job."""
    lines = []
    wall = data.get("wall_clock_s", 0.0)
    suffix = " (deleted; post-mortem)" if data.get("deleted") else ""
    lines.append(f"TrainJob {data['job']} — timeline, "
                 f"{wall:.3f}s journaled wall clock{suffix}")
    # All times render as offsets from the submit anchor — absolute wall
    # clocks belong in --json, not a terminal table.
    t0 = data.get("submitted_at", 0.0)
    phases = data.get("phases") or []
    if phases:
        lines.append("")
        lines.append(f"  {'PHASE':<10} {'START':>10} {'END':>10} "
                     f"{'SECONDS':>10}  ")
        for p in phases:
            frac = (p["seconds"] / wall) if wall > 0 else 0.0
            bar = "#" * max(1, int(round(frac * 30)))
            lines.append(f"  {p['phase']:<10} {p['start'] - t0:>9.3f}s "
                         f"{p['end'] - t0:>9.3f}s {p['seconds']:>9.3f}s"
                         f"  {bar}")
    if show_events:
        events = data.get("events") or []
        dropped = data.get("dropped", 0)
        lines.append("")
        lines.append(f"events: {len(events)}"
                     + (f" (+{dropped} dropped — oldest fell off the ring)"
                        if dropped else ""))
        for ev in events:
            attrs = ev.get("attrs") or {}
            extra = " ".join(f"{k}={v}" for k, v in attrs.items())
            rid = ev.get("reconcile_id")
            tag = f" [rid={rid}]" if rid else ""
            lines.append(f"  +{ev['offset_s']:>9.3f}s  {ev['event']:<16}"
                         f" {extra}{tag}".rstrip())
    trainer = data.get("trainer")
    if trainer and trainer.get("replicas"):
        lines.append("")
        lines.append("trainer telemetry:")
        for pod, s in sorted(trainer["replicas"].items()):
            bits = []
            for k in ("startup_s", "step", "loss", "steady_steps_per_sec"):
                if s.get(k) is not None:
                    bits.append(f"{k}={s[k]}")
            lines.append(f"  {pod}: " + " ".join(bits))
    return "\n".join(lines)


def cmd_timeline(args) -> int:
    path = f"/api/trainjobs/{args.namespace}/{args.name}/timeline"
    try:
        data = _api_get(args.server, path)
    except urllib.error.HTTPError as e:
        print(f"timeline: {e.code} {e.read().decode(errors='replace')}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(data, indent=2, default=str))
        return 0
    print(render_timeline(data, show_events=not args.no_events))
    return 0


def cmd_submit(args) -> int:
    if _manifest_kind(args.manifest) == "InferenceService":
        with open(args.manifest) as f:
            svc = compat.infsvc_from_yaml(f.read())
        body = json.dumps(compat.infsvc_to_dict(svc)).encode()
        req = urllib.request.Request(
            f"http://{args.server}/api/inferenceservices",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            print(json.dumps(json.loads(r.read()), indent=2)[:2000])
        return 0
    job = _load_job(args.manifest)
    body = json.dumps(compat.job_to_dict(job)).encode()
    req = urllib.request.Request(
        f"http://{args.server}/api/trainjobs",
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        print(json.dumps(json.loads(r.read()), indent=2)[:2000])
    return 0


def cmd_scale(args) -> int:
    """Elastic scaling: `tpujob scale myjob worker=4 ps=2`. The reconciler
    rolls live pods onto the new topology (beyond the reference, which kept
    replica counts static — SURVEY §5)."""
    replicas = {}
    for spec in args.replicas:
        rname, eq, n = spec.partition("=")
        if not eq or not n.isdigit():
            print(f"scale: expected TYPE=N, got {spec!r}", file=sys.stderr)
            return 2
        replicas[rname] = int(n)
    body = json.dumps({"replicas": replicas}).encode()
    req = urllib.request.Request(
        f"http://{args.server}/api/trainjobs/{args.namespace}/{args.name}/scale",
        data=body,
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            data = json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(f"scale: {e.code} {e.read().decode(errors='replace')}",
              file=sys.stderr)
        return 1
    counts = {
        t: s.get("replicas")
        for t, s in data["manifest"]["spec"]["replicaSpecs"].items()
    }
    print(json.dumps({"scaled": counts}))
    return 0


def cmd_suspend(args) -> int:
    """Suspend/resume (batch/v1 Job.spec.suspend shape, beyond the
    reference): suspend frees every pod and the whole TPU slice while the
    job object and its checkpoints persist; resume recreates the pods and
    the trainers continue the trajectory."""
    verb = "suspend" if args.cmd == "suspend" else "resume"
    req = urllib.request.Request(
        f"http://{args.server}/api/trainjobs/{args.namespace}/{args.name}/{verb}",
        data=b"{}", headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            json.loads(r.read())
    except urllib.error.HTTPError as e:
        print(f"{verb}: {e.code} {e.read().decode(errors='replace')}",
              file=sys.stderr)
        return 1
    print(json.dumps({verb: f"{args.namespace}/{args.name}"}))
    return 0


def cmd_version(args) -> int:
    from tf_operator_tpu.version import version_string

    print(version_string())
    return 0


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="tpujob")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("validate")
    p.add_argument("manifest")
    p.set_defaults(fn=cmd_validate)

    p = sub.add_parser("run")
    p.add_argument("manifest")
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--log-dir", default=None)
    p.add_argument("--tpu-slices", nargs="*", default=None,
                   help="gang-admission slice fleet, e.g. v5e-8 v5e-8")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("operator")
    p.add_argument("--threadiness", type=int, default=2)  # options.go default
    p.add_argument("--queue-shards", type=int, default=1,
                   help="shard the reconcile workqueue (fleet scale: "
                        "workers stop contending on one queue lock; keys "
                        "route to stable shards). 1 = the classic single "
                        "queue")
    p.add_argument("--fleet-config", default=None,
                   help="fleet scheduling policy YAML (priorityClasses, "
                        "per-namespace quotas, weighted queues, "
                        "preemptionCooldownSeconds — docs/scheduling.md); "
                        "with --tpu-slices the fleet scheduler arbitrates "
                        "admission and preemption")
    p.add_argument("--monitoring-port", type=int, default=8443)
    p.add_argument("--bind", default="127.0.0.1",
                   help="REST/metrics bind address; the API is "
                        "unauthenticated, so non-loopback is an explicit "
                        "opt-in (probes/port-forward enter via loopback)")
    p.add_argument("--enable-gang-scheduling", action="store_true")
    p.add_argument("--gang-scheduler-name", default="volcano")
    p.add_argument("--enable-leader-election", action="store_true")
    p.add_argument("--lock-file", default="/tmp/tpujob-operator.lock")
    # Lease-election timing (K8s substrate); defaults match the reference
    # (server.go:157-182: 15s lease / 5s renew / 3s retry).
    p.add_argument("--lease-duration", type=float, default=15.0)
    p.add_argument("--lease-renew-period", type=float, default=5.0)
    p.add_argument("--lease-retry-period", type=float, default=3.0)
    p.add_argument("--lease-renew-deadline", type=float, default=None,
                   help="leader deposes itself after this long without a "
                        "renew (default 2/3 of --lease-duration; must be "
                        "under it so deposition beats standby takeover)")
    p.add_argument("--log-dir", default=None)
    p.add_argument("--tpu-slices", nargs="*", default=None)
    p.add_argument("--kube-api", default=None,
                   help="K8s API server URL: run against a real cluster "
                        "(pods become cluster pods) instead of the "
                        "local-process runtime")
    p.add_argument("--in-cluster", action="store_true",
                   help="use the pod service-account config (deployment "
                        "inside the cluster, ref server.go:99)")
    p.add_argument("--kube-token", default=None)
    p.add_argument("--kube-insecure", action="store_true")
    p.add_argument("--kube-api-qps", type=float, default=5.0,
                   help="client-side max QPS to the API server (reference "
                        "--qps, options.go:81; 0 disables throttling)")
    p.add_argument("--kube-api-burst", type=int, default=10,
                   help="token-bucket burst above --kube-api-qps "
                        "(reference --burst, options.go:82)")
    p.add_argument("--namespace", default=None,
                   help="restrict the operator to one namespace "
                        "(options.go namespace scope)")
    p.add_argument("--webhook-port", type=int, default=0,
                   help="serve the ValidatingAdmissionWebhook (POST "
                        "/validate) on this port; 0 disables. Register it "
                        "with manifests/webhook.yaml")
    p.add_argument("--webhook-bind", default="0.0.0.0",
                   help="webhook bind address — unlike the REST API the "
                        "apiserver must reach it over the pod network")
    p.add_argument("--webhook-cert", default=None,
                   help="TLS cert for the webhook (real clusters require "
                        "HTTPS webhooks); plain HTTP without it")
    p.add_argument("--webhook-key", default=None)
    # Flight recorder + tracing (docs/monitoring.md "Flight recorder").
    p.add_argument("--no-journal", action="store_true",
                   help="disable the per-job lifecycle journal (on by "
                        "default; bounded memory, O(1) per event)")
    p.add_argument("--journal-events", type=int, default=256,
                   help="ring capacity per job — oldest events drop "
                        "(counted in the timeline's `dropped`) beyond it")
    p.add_argument("--journal-jobs", type=int, default=4096,
                   help="max jobs journaled; least-recently-touched "
                        "jobs evict beyond it")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="record operator-side spans (reconcile passes, "
                        "scheduler decides, status flushes) and write a "
                        "Perfetto/chrome://tracing-loadable trace to "
                        "PATH on shutdown")
    p.set_defaults(fn=cmd_operator)

    p = sub.add_parser("kubelet")
    p.add_argument("--kube-api", default=None)
    p.add_argument("--in-cluster", action="store_true")
    p.add_argument("--kube-token", default=None)
    p.add_argument("--kube-insecure", action="store_true")
    p.add_argument("--namespace", default=None)
    p.add_argument("--log-dir", default=None)
    p.add_argument("--external-scheduler", action="store_true",
                   help="real-kubelet placement semantics: pods naming a "
                        "foreign schedulerName stay Pending until that "
                        "scheduler binds them (sets spec.nodeName); "
                        "without this flag the node agent starts pods on "
                        "creation (it plays scheduler+kubelet in one)")
    p.set_defaults(fn=cmd_kubelet)

    p = sub.add_parser("get")
    p.add_argument("namespace", nargs="?", default=None)
    p.add_argument("name", nargs="?", default=None)
    p.add_argument("--server", default="127.0.0.1:8443")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("submit")
    p.add_argument("manifest")
    p.add_argument("--server", default="127.0.0.1:8443")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("timeline",
                       help="causal phase view of one job from the "
                            "operator's flight recorder")
    p.add_argument("name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--server", default="127.0.0.1:8443")
    p.add_argument("--json", action="store_true",
                   help="raw timeline payload instead of the rendering")
    p.add_argument("--no-events", action="store_true",
                   help="phase breakdown only; skip the event log")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("scale")
    p.add_argument("name")
    p.add_argument("replicas", nargs="+", metavar="TYPE=N",
                   help="e.g. worker=4 ps=2")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--server", default="127.0.0.1:8443")
    p.set_defaults(fn=cmd_scale)

    for verb in ("suspend", "resume"):
        p = sub.add_parser(verb)
        p.add_argument("name")
        p.add_argument("-n", "--namespace", default="default")
        p.add_argument("--server", default="127.0.0.1:8443")
        p.set_defaults(fn=cmd_suspend)

    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)
    return ap


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except FileNotFoundError as e:
        print(f"error: {e.filename or e}: no such file", file=sys.stderr)
        return 2
    except (ValueError, OSError) as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
