"""Operator entrypoint, REST/metrics servers, leader election."""
