"""REST API + metrics HTTP server.

Dashboard-backend parity (dashboard/backend/handler/api_handler.go:42-267):
  GET    /api/trainjobs                      list all jobs (all namespaces)
  GET    /api/inferenceservices[/{ns}[/{n}]] list/get serving workloads
  POST   /api/inferenceservices              submit an InferenceService
  DELETE /api/inferenceservices/{ns}/{name}  delete a serving workload
  GET    /api/trainjobs/{ns}                 list jobs in a namespace
  GET    /api/trainjobs/{ns}/{name}          one job (spec + status + events)
  POST   /api/trainjobs                      submit a manifest (JSON body)
  POST   /api/trainjobs/{ns}/{name}/scale    elastic scaling: body
                                             {"replicas": {"Worker": 4}}
  POST   /api/trainjobs/{ns}/{name}/suspend  free every pod + the TPU slice,
                                             keep the job (checkpoints kept)
  POST   /api/trainjobs/{ns}/{name}/resume   recreate pods; trainers resume
  DELETE /api/trainjobs/{ns}/{name}          delete a job
  GET    /api/namespaces                     namespaces in use
  GET    /api/pods/{ns}                      pods in a namespace
  GET    /api/logs/{ns}/{pod}                pod logs (local runtime log files)
  GET    /api/endpoints/{ns}/{name}          replica HTTP addresses (port-map
                                             view; E2E fault-injection path)

Operator-ops parity (main.go:38-46, options.go:74):
  GET    /metrics                            Prometheus text format
  GET    /healthz                            liveness
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tf_operator_tpu.api import compat, defaults, validation
from tf_operator_tpu.api.types import TrainJob
from tf_operator_tpu.core.cluster import InMemoryCluster
from tf_operator_tpu.status import metrics


def _job_payload(cluster: InMemoryCluster, job: TrainJob,
                 telemetry=None, scheduler=None) -> dict:
    payload = {
        "manifest": compat.job_to_dict(job),
        "status": {
            "conditions": [
                {
                    "type": str(c.type),
                    "status": c.status,
                    "reason": c.reason,
                    "message": c.message,
                }
                for c in job.status.conditions
            ],
            "replicaStatuses": {
                str(rt): asdict(rs) for rt, rs in job.status.replica_statuses.items()
            },
            "startTime": job.status.start_time,
            "completionTime": job.status.completion_time,
            # Gang-recovery visibility: how many slice-wide restarts the
            # job has eaten, how many count against backoffLimit right
            # now (consecutive, reset by heartbeat progress), and any
            # pods stuck Pending past recovery.pendingTimeoutSeconds.
            "gangRestarts": job.status.gang_restarts,
            "consecutiveRestarts": job.status.consecutive_restarts,
            # Multi-slice: which slice's gang rolled, how often — the
            # "slice 3 keeps failing" signal (job-level tallies above
            # stay authoritative for backoffLimit).
            "sliceRestarts": dict(job.status.slice_restarts),
            # Which slice(s) the gang currently holds (the claim record
            # that used to live in the tpujob.dev/slice annotation).
            "sliceIds": list(job.status.slice_ids),
            "stuckPendingPods": list(job.status.stuck_pending_pods),
            # Preemption visibility (sched/): planned evictions are a
            # first-class lifecycle event, not failures.
            "preemptions": job.status.preemptions,
            "lastPreemptionTime": job.status.last_preemption_time,
        },
        "events": [
            {"type": e.type, "reason": e.reason, "message": e.message, "ts": e.timestamp}
            for e in cluster.events_for(TrainJob.KIND, job.namespace, job.name)
        ],
    }
    if telemetry is not None:
        # Data-plane telemetry read back from the pods' trainer event
        # files (telemetry/collector.py): per-replica step/loss/startup,
        # steady rates, and the round-8 step_time_s percentiles +
        # phase_breakdown. Single-job GETs only — list responses stay
        # cheap (no file IO per job per list).
        payload["telemetry"] = telemetry.job_telemetry(job.namespace, job.name)
    if scheduler is not None:
        # Fleet-scheduler view: live state (Admitted/Queued), queue,
        # priority, and — for waiters — the 1-based queue position.
        payload["scheduling"] = scheduler.job_view(job.key())
    return payload


def _infsvc_payload(cluster, svc, telemetry=None) -> dict:
    from tf_operator_tpu.api.types import InferenceService

    payload = {
        "manifest": compat.infsvc_to_dict(svc),
        "status": {
            "conditions": [
                {
                    "type": str(c.type),
                    "status": c.status,
                    "reason": c.reason,
                    "message": c.message,
                }
                for c in svc.status.conditions
            ],
            "replicas": svc.status.replicas,
            "readyReplicas": svc.status.ready_replicas,
            "desiredReplicas": svc.status.desired_replicas,
            "lastScaleTime": svc.status.last_scale_time,
            "restarts": svc.status.restarts,
            # The shared front-end tier (serve/router.py): every router
            # address, slot-ordered; clients round-robin with connect-
            # phase failover. The legacy singular is endpoint 0.
            "routerEndpoint": svc.status.router_endpoint,
            "routerEndpoints": list(svc.status.router_endpoints),
            "startTime": svc.status.start_time,
        },
        "events": [
            {"type": e.type, "reason": e.reason, "message": e.message,
             "ts": e.timestamp}
            for e in cluster.events_for(
                InferenceService.KIND, svc.namespace, svc.name)
        ],
    }
    if telemetry is not None:
        load_fn = getattr(telemetry, "service_load", None)
        if load_fn is not None:
            # Per-replica serve stats (inflight, request totals, latency
            # percentiles) — the same snapshot the autoscaler consumes.
            payload["serving"] = load_fn(svc.namespace, svc.name)
    return payload


class ApiServer:
    def __init__(self, cluster: InMemoryCluster, port: int = 8443,
                 log_dir: str | None = None, runtime=None,
                 bind: str = "127.0.0.1", telemetry=None, scheduler=None,
                 fleet=None, controllers=()):
        self.cluster = cluster
        self.log_dir = log_dir
        self.runtime = runtime  # LocalProcessRuntime, for the endpoints view
        # Fleet scheduler (sched.FleetScheduler): serves per-job queue
        # position on single-job GETs and the whole-fleet /api/queues view.
        self.scheduler = scheduler
        # Workload controllers, for /debug/state introspection: their
        # StatusWriters' pending coalescing windows and (serve) router
        # backends. Optional — the endpoint degrades to what's wired.
        self.controllers = list(controllers)
        # Fleet policy for submit-time validation. Passed separately so a
        # --fleet-config-only deployment (no slices -> no scheduler) still
        # 400s a typo'd priorityClass at the API edge.
        self.fleet = fleet or (scheduler.policy
                               if scheduler is not None else None)
        # Trainer telemetry rides the same log_dir the runtime writes pod
        # metrics files into; without a log_dir there is nothing to read.
        # Callers that already own a collector for the same log_dir (the
        # operator's hang-watchdog heartbeat source) pass it in so one
        # instance serves both reads.
        self.telemetry = telemetry
        if self.telemetry is None and log_dir:
            from tf_operator_tpu.telemetry.collector import TelemetryCollector

            self.telemetry = TelemetryCollector(log_dir)
        # Long-poll support (event-driven waits, VERDICT r3 next #3): any
        # job/pod change bumps a generation under the condition; waiters
        # re-check their predicate per bump instead of sleep-polling over
        # HTTP. Cluster reads happen OUTSIDE the condition (the cluster
        # fires handlers from its own locked sections — nesting its lock
        # inside ours would be an AB-BA deadlock); the generation check
        # closes the read->wait race window.
        self._events = threading.Condition()
        self._events_gen = 0

        def _notify(*_a) -> None:
            with self._events:
                self._events_gen += 1
                self._events.notify_all()

        # JOB events only: every long-poll predicate reads job state
        # (conditions, deletion). Pod events are deliberately NOT
        # subscribed — the in-memory substrate deep-copies event payloads
        # per handler, and pod status churn is the reconcile loop's
        # hottest path; a bump per pod write would be pure wasted copying.
        from tf_operator_tpu.core.cluster import KIND_JOB

        cluster.on_add(KIND_JOB, _notify)
        cluster.on_update(KIND_JOB, _notify)
        cluster.on_delete(KIND_JOB, _notify)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, payload, code=200, content_type="application/json"):
                body = (
                    payload.encode()
                    if isinstance(payload, str)
                    else json.dumps(payload).encode()
                )
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _get_job_maybe_wait(self, ns: str, name: str) -> None:
                """GET one job; with `waitCondition=Succeeded,Failed` (or
                `waitDeleted=1`) + `timeoutSeconds=N`, LONG-POLL: the
                response is held until the predicate is true or the window
                expires (408 with the current state). Event-driven — the
                harness's waits ride cluster update events instead of
                client-side sleep loops."""
                import time as _time
                import urllib.parse as _up

                q = _up.parse_qs(self.path.partition("?")[2])
                want = q.get("waitCondition", [None])[0]
                wait_deleted = q.get("waitDeleted", [None])[0]
                timeout = min(float(q.get("timeoutSeconds", ["0"])[0]), 300.0)
                deadline = _time.monotonic() + timeout
                wanted = set((want or "").split(",")) - {""}
                while True:
                    with outer._events:
                        gen = outer._events_gen
                    job = outer.cluster.try_get_job(ns, name)
                    if wait_deleted:
                        if job is None:
                            return self._send({"deleted": True})
                    elif job is None:
                        return self._send({"error": "not found"}, 404)
                    elif not wanted or any(
                        c.status and str(c.type) in wanted
                        for c in job.status.conditions
                    ):
                        return self._send(_job_payload(outer.cluster, job, outer.telemetry, outer.scheduler))
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        payload = {"timeout": True}
                        if job is not None:
                            payload["job"] = _job_payload(outer.cluster, job, outer.telemetry, outer.scheduler)
                        return self._send(payload, 408)
                    with outer._events:
                        if outer._events_gen == gen:
                            outer._events.wait(min(remaining, 1.0))

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                try:
                    if not parts or parts[0] == "ui":
                        # Dashboard SPA (reference Aux-A: /tfjobs/ui/).
                        import os

                        page = os.path.join(
                            os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))),
                            "dashboard", "index.html",
                        )
                        with open(page, "rb") as f:
                            self._send(f.read().decode(),
                                       content_type="text/html; charset=utf-8")
                    elif parts == ["metrics"]:
                        if outer.telemetry is not None:
                            # Pull-model: trainer gauges refresh from the
                            # pods' metrics files on scrape, never on a
                            # hot path (labels bounded by live jobs).
                            outer.telemetry.refresh_gauges(outer.cluster)
                        self._send(metrics.DEFAULT.expose(), content_type="text/plain")
                    elif parts == ["healthz"]:
                        self._send({"ok": True})
                    elif parts == ["api", "namespaces"]:
                        ns = sorted({j.namespace for j in outer.cluster.list_jobs()})
                        self._send({"namespaces": ns})
                    elif parts == ["api", "queues"]:
                        # Whole-fleet scheduler view: per-queue depths and
                        # weights, the globally-ranked waiting list (with
                        # positions), held slices, in-flight evictions,
                        # and the self-audit stats (inversions /
                        # quota_violations must read 0).
                        if outer.scheduler is None:
                            self._send({"error": "no fleet scheduler"}, 404)
                        else:
                            self._send(outer.scheduler.snapshot())
                    elif parts[:2] == ["api", "trainjobs"] and len(parts) == 2:
                        self._send(
                            {
                                "items": [
                                    _job_payload(outer.cluster, j)
                                    for j in outer.cluster.list_jobs()
                                ]
                            }
                        )
                    elif parts[:2] == ["api", "trainjobs"] and len(parts) == 3:
                        self._send(
                            {
                                "items": [
                                    _job_payload(outer.cluster, j)
                                    for j in outer.cluster.list_jobs(parts[2])
                                ]
                            }
                        )
                    elif (parts[:2] == ["api", "trainjobs"]
                          and len(parts) == 5 and parts[4] == "timeline"):
                        tl = outer.timeline(parts[2], parts[3])
                        if tl is None:
                            self._send({"error": "no journal for job"}, 404)
                        else:
                            self._send(tl)
                    elif parts == ["debug", "state"]:
                        self._send(outer.debug_state())
                    elif parts[:2] == ["api", "trainjobs"] and len(parts) == 4:
                        self._get_job_maybe_wait(parts[2], parts[3])
                    elif (parts[:2] == ["api", "inferenceservices"]
                          and len(parts) in (2, 3)):
                        items = outer.cluster.list_infsvcs(
                            parts[2] if len(parts) == 3 else None)
                        self._send({"items": [
                            _infsvc_payload(outer.cluster, s0)
                            for s0 in items
                        ]})
                    elif (parts[:2] == ["api", "inferenceservices"]
                          and len(parts) == 4):
                        svc = outer.cluster.try_get_infsvc(
                            parts[2], parts[3])
                        if svc is None:
                            self._send({"error": "not found"}, 404)
                        else:
                            self._send(_infsvc_payload(
                                outer.cluster, svc, outer.telemetry))
                    elif parts[:2] == ["api", "pods"] and len(parts) == 3:
                        pods = outer.cluster.list_pods(parts[2])
                        self._send(
                            {
                                "items": [
                                    {
                                        "name": p.name,
                                        "phase": str(p.status.phase),
                                        "labels": p.metadata.labels,
                                        "restartCount": sum(
                                            c.restart_count
                                            for c in p.status.container_statuses
                                        ),
                                    }
                                    for p in pods
                                ]
                            }
                        )
                    elif parts[:2] == ["api", "endpoints"] and len(parts) == 4:
                        if outer.runtime is None:
                            # K8s substrate: the node agent publishes each
                            # replica's dialable address on the pod (its
                            # stand-in for status.podIP) — read it back.
                            from tf_operator_tpu.core.cluster import (
                                ENDPOINT_ANNOTATION,
                            )

                            ns, name = parts[2], parts[3]
                            eps = {}
                            for pod in outer.cluster.list_pods(
                                ns, {"job-name": name}
                            ):
                                ep = pod.metadata.annotations.get(
                                    ENDPOINT_ANNOTATION
                                )
                                if ep:
                                    eps[pod.name] = ep
                            self._send({"endpoints": eps})
                            return
                        ns, name = parts[2], parts[3]
                        pm = outer.runtime.port_map(name, ns)
                        if pm is None:
                            self._send({"endpoints": {}})
                            return
                        eps = {}
                        for pod in outer.cluster.list_pods(ns):
                            if pod.metadata.labels.get("job-name") != name:
                                continue
                            host = f"{pod.name}.{ns}.svc"
                            for h, mapping in pm.ports.items():
                                if h.startswith(host) and mapping:
                                    port_no = mapping.get(2222) or sorted(
                                        mapping.values()
                                    )[0]
                                    eps[pod.name] = f"127.0.0.1:{port_no}"
                        self._send({"endpoints": eps})
                    elif parts[:2] == ["api", "logs"] and len(parts) == 4:
                        ns, pod_name = parts[2], parts[3]
                        if hasattr(outer.cluster, "pod_logs"):
                            # K8s substrate: proxy the pod-log subresource
                            # (ref dashboard api_handler.go:237) — the local
                            # log_dir is dead in --kube-api mode.
                            from tf_operator_tpu.core.cluster import (
                                ApiError,
                                NotFoundError,
                            )

                            try:
                                # tailLines keeps the truncation server-side
                                # (a long run's full log never crosses the
                                # wire just to be sliced here).
                                text = outer.cluster.pod_logs(
                                    ns, pod_name, tail_lines=1000
                                )
                            except NotFoundError:
                                self._send({"error": "no logs"}, 404)
                                return
                            except (ApiError, OSError) as e:
                                self._send({"error": str(e)}, 502)
                                return
                            self._send(text[-65536:],
                                       content_type="text/plain")
                            return
                        if outer.log_dir is None:
                            self._send({"error": "log collection disabled"}, 404)
                            return
                        import os

                        path = os.path.join(outer.log_dir, f"{ns}_{pod_name}.log")
                        if not os.path.exists(path):
                            self._send({"error": "no logs"}, 404)
                            return
                        with open(path, "rb") as f:
                            data = f.read()[-65536:]
                        self._send(data.decode(errors="replace"), content_type="text/plain")
                    else:
                        self._send({"error": "not found"}, 404)
                except Exception as e:  # surface handler bugs as 500s, not hangs
                    self._send({"error": f"{type(e).__name__}: {e}"}, 500)

            def do_POST(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                # POST /api/trainjobs/{ns}/{name}/scale {"replicas": {"Worker": 4}}
                # -> elastic scaling: the reconciler rolls/creates/deletes pods
                # to the new counts (core/trainjob_controller.py).
                # POST /api/trainjobs/{ns}/{name}/suspend | /resume: tear
                # down / recreate every pod, keeping the job (+ checkpoints).
                if (parts[:2] == ["api", "trainjobs"] and len(parts) == 5
                        and parts[4] in ("suspend", "resume")):
                    try:
                        job = outer.cluster.try_get_job(parts[2], parts[3])
                        if job is None:
                            self._send({"error": "not found"}, 404)
                            return
                        job.spec.run_policy.suspend = parts[4] == "suspend"
                        updated = outer.cluster.update_job(job)
                        self._send(_job_payload(outer.cluster, updated))
                    except Exception as e:
                        self._send({"error": f"{type(e).__name__}: {e}"}, 400)
                    return
                if (parts[:2] == ["api", "trainjobs"] and len(parts) == 5
                        and parts[4] == "scale"):
                    try:
                        length = int(self.headers.get("Content-Length", "0"))
                        body = json.loads(self.rfile.read(length))
                        job = outer.cluster.try_get_job(parts[2], parts[3])
                        if job is None:
                            self._send({"error": "not found"}, 404)
                            return
                        for rname, count in (body.get("replicas") or {}).items():
                            rtype = defaults.canonical_replica_type(rname)
                            spec = job.spec.replica_specs.get(
                                rtype if rtype is not None else rname
                            )
                            if spec is None:
                                self._send({"error": f"no replica type {rname}"}, 400)
                                return
                            spec.replicas = int(count)
                        problems = validation.validate_job(job)
                        if problems:
                            self._send({"error": "invalid scale",
                                        "problems": problems}, 400)
                            return
                        updated = outer.cluster.update_job(job)
                        self._send(_job_payload(outer.cluster, updated))
                    except Exception as e:
                        self._send({"error": f"{type(e).__name__}: {e}"}, 400)
                    return
                if parts[:2] == ["api", "inferenceservices"]:
                    try:
                        length = int(self.headers.get("Content-Length", "0"))
                        raw = self.rfile.read(length)
                        ctype = self.headers.get("Content-Type",
                                                 "application/json")
                        if "yaml" in ctype:
                            svc = compat.infsvc_from_yaml(raw.decode())
                        else:
                            svc = compat.infsvc_from_dict(json.loads(raw))
                        defaults.set_infsvc_defaults(svc)
                        problems = validation.validate_inference_service(
                            svc, fleet=outer.fleet)
                        if problems:
                            self._send({"error": "invalid InferenceService",
                                        "problems": problems}, 400)
                            return
                        created = outer.cluster.create_infsvc(svc)
                        self._send(_infsvc_payload(outer.cluster, created),
                                   201)
                    except Exception as e:
                        self._send({"error": f"{type(e).__name__}: {e}"},
                                   400)
                    return
                if parts[:2] != ["api", "trainjobs"]:
                    self._send({"error": "not found"}, 404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    raw = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "application/json")
                    if "yaml" in ctype:
                        job = compat.job_from_yaml(raw.decode())
                    else:
                        job = compat.job_from_dict(json.loads(raw))
                    # Admission-time validation (SURVEY.md §7: validate at the
                    # API edge instead of the reference's in-controller
                    # invalid-spec status write-back, informer.go:82). With a
                    # fleet scheduler its policy joins the invariants: a
                    # typo'd priorityClass is a 400 here, not a silent
                    # default-priority run.
                    defaults.set_defaults(job)
                    problems = validation.validate_job(job, fleet=outer.fleet)
                    if problems:
                        self._send({"error": "invalid TrainJob",
                                    "problems": problems}, 400)
                        return
                    created = outer.cluster.create_job(job)
                    self._send(_job_payload(outer.cluster, created), 201)
                except Exception as e:
                    self._send({"error": f"{type(e).__name__}: {e}"}, 400)

            def do_DELETE(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                if parts[:2] == ["api", "trainjobs"] and len(parts) == 4:
                    try:
                        outer.cluster.delete_job(parts[2], parts[3])
                        self._send({"deleted": f"{parts[2]}/{parts[3]}"})
                    except Exception as e:
                        self._send({"error": str(e)}, 404)
                elif (parts[:2] == ["api", "inferenceservices"]
                        and len(parts) == 4):
                    try:
                        outer.cluster.delete_infsvc(parts[2], parts[3])
                        self._send({"deleted": f"{parts[2]}/{parts[3]}"})
                    except Exception as e:
                        self._send({"error": str(e)}, 404)
                else:
                    self._send({"error": "not found"}, 404)

        self._server = ThreadingHTTPServer((bind, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------- flight recorder views

    def timeline(self, ns: str, name: str) -> dict | None:
        """The job's flight-recorder timeline: journaled events (wall-
        clock anchored), the contiguous phase breakdown, and — when a
        collector is wired — the trainer-side telemetry merged in. None
        when the job was never journaled (or its ring expired)."""
        from tf_operator_tpu.telemetry import journal as journal_lib

        return journal_lib.timeline_payload(
            ns, name, telemetry=self.telemetry)

    def debug_state(self) -> dict:
        """One JSON snapshot of the control plane's live internals:
        scheduler queues, allocator claims, pending StatusWriter
        coalescing windows, serve-router backends, journal accounting."""
        from tf_operator_tpu.telemetry import journal as journal_lib

        state: dict = {"journal": journal_lib.get_journal().snapshot()}
        if self.scheduler is not None:
            state["scheduler"] = self.scheduler.snapshot()
            alloc = getattr(self.scheduler, "allocator", None)
        else:
            alloc = None
        if alloc is None:
            for c in self.controllers:
                alloc = getattr(c, "slice_allocator", None)
                if alloc is not None:
                    break
        if alloc is not None:
            state["allocator"] = alloc.snapshot()
        writers = {}
        routers = {}
        for c in self.controllers:
            sw = getattr(c, "_status_writer", None)
            if sw is not None:
                writers[sw.kind] = {"pending": sw.pending(),
                                    "window_s": sw.window}
            snap = getattr(c, "router_snapshot", None)
            if callable(snap):
                routers.update(snap())
        state["status_writers"] = writers
        state["routers"] = routers
        return state

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
