"""ctypes bindings for the native (C++) runtime library.

The native tier implements the operator's hot-loop primitives — rate-limited
workqueue, expectations cache, exit-code policy (semantics of the reference's
jobcontroller.go:110-133 / train_util.go:18-55) — and the local executor's
process supervisor (setsid process groups, pidfd waits, whole-tree kills).
Source: native/tpujob_native.cc, built by native/Makefile.

Loading policy:
  - First import tries `native/build/libtpujob_native.so`; if missing/stale
    and a C++ toolchain is present, it is built on the fly (one `make`
    invocation, cached thereafter).
  - Failure is non-fatal: `load()` returns None and callers fall back to the
    pure-Python implementations with identical semantics.
  - TPUJOB_NATIVE=0 disables the native path; TPUJOB_NATIVE=require makes a
    load failure raise (used in CI to prove the native path is exercised).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libtpujob_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_attempted = False
_load_error: str | None = None


def _build() -> bool:
    src = _NATIVE_DIR / "tpujob_native.cc"
    if not src.exists():
        return False
    if _LIB_PATH.exists() and _LIB_PATH.stat().st_mtime >= src.stat().st_mtime:
        return True
    try:
        r = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    if r.returncode != 0:
        global _load_error
        _load_error = f"native build failed:\n{r.stdout}\n{r.stderr}"
        return False
    return _LIB_PATH.exists()


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    lib.tq_new.restype = c.c_void_p
    lib.tq_new.argtypes = [c.c_double, c.c_int, c.c_double, c.c_double]
    lib.tq_free.argtypes = [c.c_void_p]
    lib.tq_add.argtypes = [c.c_void_p, c.c_char_p]
    lib.tq_add_after.argtypes = [c.c_void_p, c.c_char_p, c.c_double]
    lib.tq_add_rate_limited.argtypes = [c.c_void_p, c.c_char_p]
    lib.tq_forget.argtypes = [c.c_void_p, c.c_char_p]
    lib.tq_num_requeues.restype = c.c_int
    lib.tq_num_requeues.argtypes = [c.c_void_p, c.c_char_p]
    lib.tq_get.restype = c.c_int
    lib.tq_get.argtypes = [c.c_void_p, c.c_double, c.c_int, c.c_char_p, c.c_int]
    lib.tq_done.argtypes = [c.c_void_p, c.c_char_p]
    lib.tq_shutdown.argtypes = [c.c_void_p]
    lib.tq_len.restype = c.c_int
    lib.tq_len.argtypes = [c.c_void_p]

    lib.te_new.restype = c.c_void_p
    lib.te_free.argtypes = [c.c_void_p]
    lib.te_expect.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.te_raise.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.te_observe.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.te_satisfied.restype = c.c_int
    lib.te_satisfied.argtypes = [c.c_void_p, c.c_char_p]
    lib.te_delete.argtypes = [c.c_void_p, c.c_char_p]

    lib.tx_is_retryable.restype = c.c_int
    lib.tx_is_retryable.argtypes = [c.c_int]

    lib.ts_new.restype = c.c_void_p
    lib.ts_free.argtypes = [c.c_void_p]
    lib.ts_spawn.restype = c.c_long
    lib.ts_spawn.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_char_p),
        c.POINTER(c.c_char_p),
        c.c_char_p,
        c.c_char_p,
    ]
    lib.ts_poll.restype = c.c_int
    lib.ts_poll.argtypes = [c.c_void_p, c.c_long]
    lib.ts_wait.restype = c.c_int
    lib.ts_wait.argtypes = [c.c_void_p, c.c_long, c.c_double, c.POINTER(c.c_int)]
    lib.ts_exit_code.restype = c.c_int
    lib.ts_exit_code.argtypes = [c.c_void_p, c.c_long]
    lib.ts_signal.argtypes = [c.c_void_p, c.c_long, c.c_int]
    lib.ts_release.argtypes = [c.c_void_p, c.c_long]


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted, _load_error
    mode = os.environ.get("TPUJOB_NATIVE", "1").lower()
    if mode in ("0", "off", "false"):
        return None
    with _lock:
        if _load_attempted:
            if _lib is None and mode == "require":
                raise RuntimeError(f"TPUJOB_NATIVE=require: {_load_error}")
            return _lib
        _load_attempted = True
        try:
            if _build():
                lib = ctypes.CDLL(str(_LIB_PATH))
                _declare(lib)
                _lib = lib
        except OSError as e:
            _load_error = str(e)
        if _lib is None and mode == "require":
            raise RuntimeError(
                f"TPUJOB_NATIVE=require but native library unavailable: {_load_error}"
            )
        return _lib


def available() -> bool:
    return load() is not None


def loaded_or_built() -> bool:
    """True if the library is loaded or its .so already exists on disk.
    Never triggers a build — safe for fast paths like `tpujob version`."""
    if _lib is not None:
        return True
    return _LIB_PATH.exists()


# ---------------------------------------------------------------------------
# Wrappers with the exact interfaces of the pure-Python implementations
# ---------------------------------------------------------------------------


class NativeRateLimitingQueue:
    """Drop-in for core.workqueue.RateLimitingQueue (string items)."""

    def __init__(self, qps: float = 10.0, burst: int = 100,
                 base_delay: float = 0.005, max_delay: float = 1000.0):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._q = self._lib.tq_new(qps, burst, base_delay, max_delay)

    def add(self, item: str) -> None:
        self._lib.tq_add(self._q, item.encode())

    def add_after(self, item: str, delay: float) -> None:
        self._lib.tq_add_after(self._q, item.encode(), delay)

    def add_rate_limited(self, item: str) -> None:
        self._lib.tq_add_rate_limited(self._q, item.encode())

    def forget(self, item: str) -> None:
        self._lib.tq_forget(self._q, item.encode())

    def num_requeues(self, item: str) -> int:
        return self._lib.tq_num_requeues(self._q, item.encode())

    def get(self, timeout: float | None = None) -> str | None:
        # tq_get needs a per-call buffer: concurrent workers share the queue.
        buf = ctypes.create_string_buffer(4096)
        r = self._lib.tq_get(
            self._q,
            -1.0 if timeout is None else timeout,
            1 if timeout is None else 0,
            buf,
            len(buf),
        )
        return buf.value.decode() if r == 1 else None

    def done(self, item: str) -> None:
        self._lib.tq_done(self._q, item.encode())

    def shut_down(self) -> None:
        self._lib.tq_shutdown(self._q)

    def __len__(self) -> int:
        return self._lib.tq_len(self._q)

    def __del__(self):
        lib, q = getattr(self, "_lib", None), getattr(self, "_q", None)
        if lib is not None and q:
            lib.tq_free(q)
            self._q = None


class NativeControllerExpectations:
    """Drop-in for core.expectations.ControllerExpectations."""

    def __init__(self) -> None:
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._e = self._lib.te_new()

    def expect_creations(self, key: str, n: int) -> None:
        self._lib.te_expect(self._e, key.encode(), n, 0)

    def expect_deletions(self, key: str, n: int) -> None:
        self._lib.te_expect(self._e, key.encode(), 0, n)

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        self._lib.te_raise(self._e, key.encode(), adds, dels)

    def creation_observed(self, key: str) -> None:
        self._lib.te_observe(self._e, key.encode(), 1, 0)

    def deletion_observed(self, key: str) -> None:
        self._lib.te_observe(self._e, key.encode(), 0, 1)

    def satisfied(self, key: str) -> bool:
        return bool(self._lib.te_satisfied(self._e, key.encode()))

    def delete_expectations(self, key: str) -> None:
        self._lib.te_delete(self._e, key.encode())

    def __del__(self):
        lib, e = getattr(self, "_lib", None), getattr(self, "_e", None)
        if lib is not None and e:
            lib.te_free(e)
            self._e = None


def native_is_retryable_exit_code(code: int) -> bool:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return bool(lib.tx_is_retryable(code))


class NativeProcess:
    """Handle for one supervised process (whole process group)."""

    def __init__(self, supervisor: "NativeSupervisor", pid: int):
        self._sup = supervisor
        self.pid = pid
        self._exit_code: int | None = None

    def poll(self) -> int | None:
        if self._exit_code is not None:
            return self._exit_code
        r = self._sup._lib.ts_poll(self._sup._s, self.pid)
        if r == 1:
            self._exit_code = self._sup._lib.ts_exit_code(self._sup._s, self.pid)
        return self._exit_code

    def wait(self, timeout: float | None = None) -> int:
        if self._exit_code is not None:
            return self._exit_code
        code = ctypes.c_int(0)
        r = self._sup._lib.ts_wait(
            self._sup._s, self.pid, -1.0 if timeout is None else timeout,
            ctypes.byref(code),
        )
        if r == 1:
            self._exit_code = code.value
            return self._exit_code
        if r == 0:
            raise TimeoutError(f"pid {self.pid} still running after {timeout}s")
        # Released concurrently (e.g. the owning thread reaped + released
        # while we waited): the cached code is the truth if we have it.
        if self._exit_code is not None:
            return self._exit_code
        raise ProcessLookupError(f"pid {self.pid} not supervised")

    def terminate(self) -> None:
        import signal as _sig

        self._sup._lib.ts_signal(self._sup._s, self.pid, int(_sig.SIGTERM))

    def kill(self) -> None:
        import signal as _sig

        self._sup._lib.ts_signal(self._sup._s, self.pid, int(_sig.SIGKILL))

    def send_signal(self, sig: int) -> None:
        self._sup._lib.ts_signal(self._sup._s, self.pid, int(sig))

    def release(self) -> None:
        self._sup._lib.ts_release(self._sup._s, self.pid)


class NativeSupervisor:
    """Process supervisor over the native library: children run in their own
    session/process group (signals reach the whole tree), stdio redirected to
    a log file, exits collected via pidfd."""

    def __init__(self) -> None:
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._s = self._lib.ts_new()

    @staticmethod
    def _carray(items: list[bytes]) -> "ctypes.Array":
        arr = (ctypes.c_char_p * (len(items) + 1))()
        arr[:-1] = items
        arr[-1] = None
        return arr

    def spawn(
        self,
        cmd: list[str],
        env: dict[str, str] | None = None,
        cwd: str | None = None,
        logfile: str | None = None,
    ) -> NativeProcess:
        argv = self._carray([c.encode() for c in cmd])
        envp = None
        if env is not None:
            envp = self._carray([f"{k}={v}".encode() for k, v in env.items()])
        pid = self._lib.ts_spawn(
            self._s,
            argv,
            envp,
            cwd.encode() if cwd else None,
            logfile.encode() if logfile else None,
        )
        if pid < 0:
            raise OSError(-pid, os.strerror(-pid), cmd[0])
        return NativeProcess(self, int(pid))

    def __del__(self):
        lib, s = getattr(self, "_lib", None), getattr(self, "_s", None)
        if lib is not None and s:
            lib.ts_free(s)
            self._s = None
