"""Fused ResNet bottleneck block (stride-1) as a pallas TPU kernel.

STATUS (round 3): measured NOT competitive on v5e — kept as the recorded
negative result behind docs/perf.md's ResNet analysis, with interpret-mode
numerics tests. Measurements (batch 256, stage-1 shapes 56x56x256/64,
forward only, tools-level harness):
  * fused kernel 8.9-9.4 ms vs ~2 ms for the same block inside the real
    XLA-compiled model (the unfused ghost-BN reference here is also slow —
    vmapped tiny convs — so compare against the real model, not it);
  * BN stats + fold account for ~40% of kernel time (5.3 ms without);
  * one K=576 im2col matmul instead of 9 K=64 matmuls: 6.4 ms (VMEM copy
    cost exceeds the MXU-fill gain);
  * raising --xla_tpu_scoped_vmem_limit_kib (64-96 MB) unblocks larger
    batch tiles but does not change the picture.
Root cause: at Cn=64..512 the block's matmuls underfill the MXU's 128-wide
contraction while the kernel's grid serializes per-tile epilogues
(pad-copy, stats reductions, relayouts) that XLA's native conv pipeline
hides; the HBM bytes saved (~2x on the forward wide tensors) are dwarfed
by the lost compute efficiency. The win this kernel chased is bounded by
~26% of step time (docs/perf.md traffic accounting) and the implementation
cost exceeds it on this stack.

Original motivation: ResNet-50's 1x1 convs are HBM-bound on v5e (~51
FLOP/byte vs the ~240 break-even), so XLA's one-fusion-per-conv execution
pays a full HBM round-trip for every internal tensor of a bottleneck
block — plus separate residual-add fusions (measured ~10% of step time,
docs/perf.md). This kernel runs the whole block — 1x1 reduce -> BN -> relu
-> 3x3 -> BN -> relu -> 1x1 expand -> BN -> +residual -> relu — over a
batch tile held in VMEM: the wide input is read once, the wide output
written once, and the narrow intermediates never touch HBM.

Batch norm inside the kernel is GHOST batch norm: statistics are computed
per batch tile (the grid unit), not over the global batch — the same
numerics as the reference's per-worker BN under MultiWorkerMirroredStrategy
(SURVEY.md §2: distribution_strategy examples), where each worker
normalizes over its local shard. Running statistics are aggregated across
tiles outside the kernel, so eval-mode normalization matches the full-batch
moments. Tile sizes (docstring of `default_tile`) keep per-BN sample counts
>= 3k — far past where ghost BN matters.

The 3x3 conv is 9 shifted matmuls over a zero-padded VMEM scratch (SAME
padding); every matmul in the block hits the MXU with M = tile*H*W rows.

Weight layouts match flax.linen.Conv kernels: w1 [1,1,Cw,Cn] -> used as
[Cw,Cn]; w2 [3,3,Cn,Cn]; w3 [1,1,Cn,Cw] -> [Cn,Cw]. BN scale/bias are f32
[C] vectors; stats outputs are raw moments (mean, mean-of-squares) so
cross-tile variance combines exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

EPS = 1e-5


def default_tile(h: int, w: int, batch: int) -> int:
    """Largest batch tile whose working set fits VMEM (~16 MB/core):
    targets ~4k spatial rows per tile; must divide the batch."""
    target = max(1, 4096 // (h * w))
    t = 1
    while t * 2 <= target and batch % (t * 2) == 0:
        t *= 2
    return t


def _bn_fold(t, scale, bias):
    """Ghost-BN over axis 0 of [N, C] f32 `t`: returns (normalized f32,
    mean, mean-of-squares) using the fold (t - m) * a + b."""
    m = jnp.mean(t, axis=0)
    m2 = jnp.mean(jnp.square(t), axis=0)
    v = jnp.maximum(m2 - jnp.square(m), 0.0)
    a = scale * jax.lax.rsqrt(v + EPS)
    return (t - m) * a + bias, m, m2


def _conv3x3(n1p, w2_ref, tb, h, w, cn):
    """9 shifted matmuls over the padded [TB,H+2,W+2,Cn] bf16 input."""
    acc = None
    for di in range(3):
        for dj in range(3):
            sh = n1p[:, di:di + h, dj:dj + w, :].reshape(tb * h * w, cn)
            p = jnp.dot(sh, w2_ref[di, dj], preferred_element_type=jnp.float32)
            acc = p if acc is None else acc + p
    return acc


def _fwd_kernel(x_ref, w1_ref, w2_ref, w3_ref, s1_ref, b1_ref, s2_ref,
                b2_ref, s3_ref, b3_ref, y_ref, st1_ref, st2_ref, st3_ref,
                n1p_scr, *, tb: int, h: int, w: int):
    cw = x_ref.shape[-1]
    cn = w1_ref.shape[-1]
    n = tb * h * w
    xt = x_ref[0]                              # [TB,H,W,Cw] bf16
    flat = xt.reshape(n, cw)
    # --- 1x1 reduce + BN1 + relu ---
    t1 = jnp.dot(flat, w1_ref[...], preferred_element_type=jnp.float32)
    z1, m1, q1 = _bn_fold(t1, s1_ref[...], b1_ref[...])
    n1 = jnp.maximum(z1, 0.0).astype(x_ref.dtype).reshape(tb, h, w, cn)
    # --- 3x3 (SAME, stride 1) via zero-padded scratch + BN2 + relu ---
    n1p_scr[...] = jnp.zeros(n1p_scr.shape, n1p_scr.dtype)
    n1p_scr[:, 1:h + 1, 1:w + 1, :] = n1
    t2 = _conv3x3(n1p_scr[...], w2_ref, tb, h, w, cn)
    z2, m2, q2 = _bn_fold(t2, s2_ref[...], b2_ref[...])
    n2 = jnp.maximum(z2, 0.0).astype(x_ref.dtype)
    # --- 1x1 expand + BN3 + residual + relu ---
    t3 = jnp.dot(n2, w3_ref[...], preferred_element_type=jnp.float32)
    z3, m3, q3 = _bn_fold(t3, s3_ref[...], b3_ref[...])
    y = jnp.maximum(z3 + flat.astype(jnp.float32), 0.0)
    y_ref[0] = y.astype(y_ref.dtype).reshape(tb, h, w, cw)
    st1_ref[0] = jnp.stack([m1, q1])
    st2_ref[0] = jnp.stack([m2, q2])
    st3_ref[0] = jnp.stack([m3, q3])


def _fwd(x, w1, w2, w3, s1, b1, s2, b2, s3, b3, tile_b, interpret):
    b, h, w, cw = x.shape
    cn = w1.shape[-1]
    tb = tile_b
    assert b % tb == 0, (b, tb)
    tiles = b // tb
    kernel = functools.partial(_fwd_kernel, tb=tb, h=h, w=w)

    def full(shape):
        return pl.BlockSpec(shape, lambda i: tuple(0 for _ in shape))

    y, st1, st2, st3 = pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((1, tb, h, w, cw), lambda i: (i, 0, 0, 0, 0)),
            full((cw, cn)), full((3, 3, cn, cn)), full((cn, cw)),
            full((cn,)), full((cn,)), full((cn,)), full((cn,)),
            full((cw,)), full((cw,)),
        ],
        out_specs=[
            pl.BlockSpec((1, tb, h, w, cw), lambda i: (i, 0, 0, 0, 0)),
            pl.BlockSpec((1, 2, cn), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 2, cn), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 2, cw), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tiles, tb, h, w, cw), x.dtype),
            jax.ShapeDtypeStruct((tiles, 2, cn), jnp.float32),
            jax.ShapeDtypeStruct((tiles, 2, cn), jnp.float32),
            jax.ShapeDtypeStruct((tiles, 2, cw), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((tb, h + 2, w + 2, cn), x.dtype)],
        interpret=interpret,
    )(x.reshape(tiles, tb, h, w, cw), w1, w2, w3, s1, b1, s2, b2, s3, b3)
    return y.reshape(b, h, w, cw), (st1, st2, st3)


def fused_bottleneck_reference(x, w1, w2, w3, s1, b1, s2, b2, s3, b3,
                               tile_b: int):
    """Pure-JAX ghost-BN reference (the kernel's semantics, unfused).
    Used for numerics tests and as the CPU/non-TPU fallback."""
    b, h, w, cw = x.shape
    tiles = b // tile_b
    xt = x.reshape(tiles, tile_b, h, w, cw)

    def block(xt):
        f32 = jnp.float32
        t1 = jax.lax.conv_general_dilated(
            xt.astype(x.dtype), w1[None, None].astype(x.dtype), (1, 1),
            "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=f32)
        z1, m1, q1 = _bn_fold(t1.reshape(-1, t1.shape[-1]), s1, b1)
        n1 = jnp.maximum(z1, 0).astype(x.dtype).reshape(t1.shape)
        t2 = jax.lax.conv_general_dilated(
            n1, w2.astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=f32)
        z2, m2, q2 = _bn_fold(t2.reshape(-1, t2.shape[-1]), s2, b2)
        n2 = jnp.maximum(z2, 0).astype(x.dtype).reshape(t2.shape)
        t3 = jax.lax.conv_general_dilated(
            n2, w3[None, None].astype(x.dtype), (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=f32)
        z3, m3, q3 = _bn_fold(t3.reshape(-1, t3.shape[-1]), s3, b3)
        y = jnp.maximum(z3.reshape(t3.shape) + xt.astype(f32), 0)
        return y.astype(x.dtype), (jnp.stack([m1, q1]), jnp.stack([m2, q2]),
                                   jnp.stack([m3, q3]))

    y, stats = jax.vmap(block)(xt)
    return y.reshape(b, h, w, cw), stats


def combine_stats(st):
    """[tiles, 2, C] raw moments -> (mean, var) over the whole batch.
    Equal-weight mean over tiles is exact because every tile has the same
    sample count (tile_b must divide the batch — asserted in _fwd)."""
    m = jnp.mean(st[:, 0], axis=0)
    q = jnp.mean(st[:, 1], axis=0)
    return m, jnp.maximum(q - jnp.square(m), 0.0)
