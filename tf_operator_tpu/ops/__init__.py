"""TPU kernels (pallas) with portable fallbacks.

Hot ops the MXU/VMEM path owns: fused flash attention (ops.flash_attention).
Every kernel has a pure-JAX reference twin used (a) as the non-TPU fallback,
(b) to pin numerics in tests (pallas interpret mode on CPU).
"""
