"""Attention dispatcher: pallas flash kernel on TPU, reference elsewhere.

Selection order for `flash_attention(q, k, v, causal)`:
  1. pallas fused kernel — default backend is TPU, pallas importable, and
     T divisible into MXU-friendly blocks
  2. pure-JAX reference (XLA still fuses well; correct everywhere)

Model code should not import this directly — use
parallel.ring_attention.make_attention_fn, which additionally routes to ring
attention when the mesh has a sequence-parallel axis.
"""

from __future__ import annotations

import jax

from tf_operator_tpu.parallel.ring_attention import attention_reference


def _pallas_eligible(q: jax.Array) -> bool:
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    t, d = q.shape[-2], q.shape[-1]
    # d%64: Mosaic pads the lane dim, so BERT-family head_dim 64 runs the
    # fused kernel (verified bit-level vs reference on v5e at d=64/128/192).
    return t >= 128 and t % 128 == 0 and d >= 64 and d % 64 == 0


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    force_pallas: bool | None = None, interpret: bool = False,
) -> jax.Array:
    """[B, H, T, D] attention with automatic kernel selection."""
    use_pallas = force_pallas if force_pallas is not None else _pallas_eligible(q)
    if use_pallas:
        from tf_operator_tpu.ops.flash_attention import flash_attention_pallas

        return flash_attention_pallas(q, k, v, causal, 128, 128, interpret)
    return attention_reference(q, k, v, causal)
