"""Attention dispatcher: pallas flash kernel on TPU, reference elsewhere.

Selection order for `flash_attention(q, k, v, causal)`:
  1. pallas fused kernel (fwd + fused bwd) — default backend is TPU, pallas
     importable, T >= 1024 and divisible into MXU-friendly blocks
  2. pure-JAX reference (XLA fuses it well at short T; correct everywhere)

The T >= 1024 threshold and the 1024 default block size are measured on
v5e (transformer-lm train step, 32k tokens/batch): XLA wins at T=256
(1141 vs 1046 ex/s), the kernel wins from T=1024 up (+10% at 1024, +13%
at 2048, +55% at 4096) and is the only path that compiles at T >= 8192.

Block-size sweep (round 3, tools/exp_flash_sweep.py on v5e, causal
fwd+bwd TF/s at 32k tokens): 1024x1024 is at/near the optimum at every
seq — seq 8k: 36.2 (vs 35.2 at 512x2048), 16k: 40.8 (40.6), 32k: 43.7
(44.1, within noise); block 2048 on either axis fails to compile the
backward (VMEM). head_dim matters far more than blocks: d=128 fills the
MXU contraction in both kernel matmuls and nearly doubles throughput
over d=64 (68.5 vs 36.2 TF/s at seq 8k) — prefer fewer, wider heads on
TPU (docs/perf.md).

Model code should not import this directly — use
parallel.ring_attention.make_attention_fn, which on meshes with a
sequence-parallel axis auto-selects between ring attention and Ulysses
all-to-all (parallel/ulysses.sp_mode) instead of calling this dispatcher.
"""

from __future__ import annotations

import os

import jax

from tf_operator_tpu.parallel.ring_attention import attention_reference

# Debug/bench override: "flash" forces the pallas kernel, "reference" forces
# the pure-JAX path, unset/"auto" selects by backend and shape.
ENV_ATTENTION = "TPUJOB_ATTENTION"


def _pallas_eligible(q: jax.Array) -> bool:
    forced = os.environ.get(ENV_ATTENTION, "").lower()
    if forced == "flash":
        return True
    if forced == "reference":
        return False
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    t, d = q.shape[-2], q.shape[-1]
    # d%64: Mosaic pads the lane dim, so BERT-family head_dim 64 runs the
    # fused kernel (verified bit-level vs reference on v5e at d=64/128/192).
    return t >= 1024 and t % 128 == 0 and d >= 64 and d % 64 == 0


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    force_pallas: bool | None = None, interpret: bool = False,
) -> jax.Array:
    """[B, H, T, D] attention with automatic kernel selection."""
    use_pallas = force_pallas if force_pallas is not None else _pallas_eligible(q)
    if use_pallas:
        from tf_operator_tpu.ops.flash_attention import flash_attention_pallas

        block = int(os.environ.get("TPUJOB_FLASH_BLOCK", "1024"))
        # TPUJOB_FLASH_INTERPRET=1: run the pallas kernels in interpret
        # mode — with TPUJOB_ATTENTION=flash this exercises the REAL kernel
        # (incl. its checkpoint_name-tagged vjp residuals) on a CPU mesh,
        # which the dryrun's remat-policy regime relies on.
        interpret = interpret or (
            os.environ.get("TPUJOB_FLASH_INTERPRET", "") == "1"
        )
        return flash_attention_pallas(q, k, v, causal, block, block, interpret)
    return attention_reference(q, k, v, causal)
