"""Fused flash attention (forward) as a pallas TPU kernel.

Why a kernel at all: naive attention materialises the [T, T] score matrix in
HBM — O(T^2) bytes against HBM bandwidth, the usual TPU bottleneck. This
kernel streams K/V blocks through VMEM and keeps the online-softmax
accumulator (m, l, acc) in VMEM scratch across the innermost grid dimension,
so HBM traffic is O(T*D) and the two matmuls per block hit the MXU back to
back (FlashAttention recurrence; kernel structure per the pallas TPU guide:
3D grid (batch*heads, q-blocks, k-blocks) with the k dimension "arbitrary"
= sequential, accumulating into scratch, output written on the last k step).

Block sizes default to 128x128 (MXU-native); causal masking prunes whole
K-blocks above the diagonal with pl.when, halving work for causal LMs.

Backward pass: fused pallas kernels (FlashAttention-2 recurrence). The
forward additionally emits the per-row logsumexp L = m + log(l); the
backward recomputes P = exp(S - L) blockwise from the saved Q/K/V and runs
two kernels — one accumulating dQ over K-blocks, one accumulating dK/dV
over Q-blocks — so backward HBM traffic is O(T*D) like the forward and all
four matmuls per block pair hit the MXU. delta = rowsum(dO * O) is
recomputed in-block from the O/dO tiles each kernel already holds (cheaper
than materializing a second lane-broadcast residual array).

Use ops.attention.flash_attention — it dispatches pallas-on-TPU / reference
elsewhere. `interpret=True` runs the same kernels on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

# Fully-masked sentinel. Defined HERE (the lowest layer); ring attention's
# merge_partials imports it so flash-produced lse values compare against the
# same constant — one definition only, and the dependency points ops <-
# parallel, matching the existing layering.
NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, *rest,
    sm_scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    save_lse: bool,
):
    if save_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
        lse_ref = None
    # lse_ref block is (block_q, 128) lane-broadcast (see the layout note
    # above _lse_out).
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]  # (BQ, D)
        k = k_ref[0]  # (BK, D)
        v = v_ref[0]  # (BK, D)
        # Zero padded tail rows of V: p is 0 there, but 0 * <pad garbage>
        # would still poison the accumulator (0*NaN=NaN).
        v_row = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(v_row < seq_k, v, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (BQ, BK)

        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < seq_k  # mask the zero-padded tail of the last K-block
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where((m_new == NEG_INF)[:, None], 0.0, p)  # fully-masked rows
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev == NEG_INF, 0.0, alpha)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    if causal:
        # Skip K-blocks entirely above the diagonal.
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == last_k)
    def _finalize():
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        if lse_ref is not None:
            # lse is the backward's residual: P = exp(S - lse) reconstructs
            # normalized probabilities blockwise. NEG_INF marks fully-masked
            # rows.
            lse = jnp.where(
                l == 0.0, NEG_INF, m + jnp.log(jnp.where(l == 0.0, 1.0, l))
            )
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)


def _check_pltpu() -> None:
    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU backend unavailable; use ops.attention.flash_attention "
            "which falls back to the reference implementation"
        )


# lse/g_lse storage layout: [BH, T, 128] f32, lane-broadcast — each row
# value replicated across the 128 lanes (the official TPU kernel stores its
# l/m residuals the same way). A compact [BH, T/128, 128] reshape layout
# would cut the bytes 128x, but Mosaic cannot lower the required in-kernel
# (block_q,) -> (block_q/128, 128) shape cast ("infer-vector-layout:
# unsupported shape cast" on v5e), so the broadcast stands.


def _lse_out(bh: int, t: int, block_q: int, index_fn):
    """(BlockSpec, ShapeDtypeStruct) for an lse-layout operand/output."""
    spec = pl.BlockSpec((1, block_q, 128), index_fn)
    shape = jax.ShapeDtypeStruct((bh, t, 128), jnp.float32)
    return spec, shape


def _lse_rows(ref) -> jax.Array:
    """Read the (block_q,) row values back from the lane-broadcast block."""
    return ref[0][:, 0]


def _lse_flat(x3) -> jax.Array:
    """[BH, T] view of a stored lse array."""
    return x3[:, :, 0]


def _lse_store(x, t: int) -> jax.Array:
    """Pack a [BH, T] f32 array into the stored lse layout."""
    return jnp.broadcast_to(x[:, :, None], (x.shape[0], t, 128))


def _flash_fwd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
    save_residuals: bool = True,
):
    """q,k,v: [BH, T, D] (batch*heads flattened). Returns (o, lse) with
    lse [BH, T, 128] lane-replicated f32, or (o, None) when
    save_residuals=False (eval/inference: skips the lse HBM writes)."""
    bh, t, d = q.shape
    tk = k.shape[1]
    sm_scale = 1.0 / (d**0.5)
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    grid = (bh, pl.cdiv(t, block_q), pl.cdiv(tk, block_k))

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=tk, save_lse=save_residuals,
    )
    _check_pltpu()
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]
    o_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    out_specs = [o_spec]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if save_residuals:
        lse_spec, lse_shape = _lse_out(bh, t, block_q, lambda b, i, j: (b, i, 0))
        out_specs.append(lse_spec)
        out_shape.append(lse_shape)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            o_spec,
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(q, k, v)
    return (out[0], out[1]) if save_residuals else (out[0], None)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
    sm_scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
    has_glse: bool,
):
    """dQ pass: grid (BH, q-blocks, k-blocks), k sequential.
    dQ_i = scale * sum_j [P_ij ∘ (dO_i V_j^T - delta_i)] K_j  (FA-2 eq. 13),
    delta_i = rowsum(dO_i ∘ O_i) computed in-block (cheaper than a second
    lane-broadcast residual array). With has_glse, an lse cotangent (from a
    downstream logsumexp-merge combiner, e.g. ring attention) adds the
    dlse/dS = P term: ds = p*(dp - delta + g_lse)."""
    if has_glse:
        glse_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        glse_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = _lse_rows(lse_ref)  # (BQ,) f32
        delta = jnp.sum(do * o_ref[0].astype(jnp.float32), axis=-1)  # (BQ,)
        if glse_ref is not None:
            delta = delta - _lse_rows(glse_ref)

        # Zero padded tail rows of K/V: p and ds are 0 at those columns, but
        # the 0 * <pad garbage> inside dp and ds@K would still poison the
        # accumulator (0*NaN=NaN).
        kv_row = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        k = jnp.where(kv_row < seq_k, k, 0)
        v = jnp.where(kv_row < seq_k, v, 0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < seq_k
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)

        p = jnp.exp(s - lse[:, None])
        p = jnp.where((lse <= NEG_INF)[:, None], 0.0, p)  # fully-masked rows
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == last_k)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *rest,
    sm_scale: float, causal: bool, block_q: int, block_k: int,
    seq_q: int, seq_k: int, has_glse: bool,
):
    """dK/dV pass: grid (BH, k-blocks, q-blocks), q sequential.
    dV_j = sum_i P_ij^T dO_i;  dK_j = scale * sum_i dS_ij^T Q_i.
    has_glse as in _bwd_dq_kernel (dK takes the p*g_lse term through dS;
    dV is unaffected — lse does not depend on V)."""
    if has_glse:
        glse_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        glse_ref = None
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    last_q = pl.num_programs(2) - 1

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = _lse_rows(lse_ref)
        delta = jnp.sum(do * o_ref[0].astype(jnp.float32), axis=-1)
        if glse_ref is not None:
            delta = delta - _lse_rows(glse_ref)

        # Padded tail rows accumulate into dk/dv through the contractions
        # below; zero the garbage at the source (0*NaN=NaN otherwise).
        q_row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, q.shape, 0)
        q = jnp.where(q_row < seq_q, q, 0)
        v_row = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(v_row < seq_k, v, 0)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # Unlike the fwd (whose padded-tail q rows fall outside the output),
        # garbage q rows here would ACCUMULATE into dk/dv — mask them too.
        valid = (k_pos < seq_k) & (q_pos < seq_q)
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)

        row_ok = (lse > NEG_INF) & (
            qi * block_q + jax.lax.broadcasted_iota(jnp.int32, lse.shape, 0) < seq_q
        )
        p = jnp.exp(s - jnp.where(row_ok, lse, 0.0)[:, None])
        p = jnp.where(valid & row_ok[:, None], p, 0.0)
        do = jnp.where(row_ok[:, None], do, 0.0)  # padded reads may be junk

        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - jnp.where(row_ok, delta, 0.0)[:, None]) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Skip Q-blocks entirely before this K-block (no q >= k pairs).
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == last_q)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(
    q: jax.Array, k: jax.Array, v: jax.Array, o: jax.Array, lse: jax.Array,
    do: jax.Array, causal: bool, block_q: int, block_k: int, interpret: bool,
    g_lse: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused backward on [BH, T, D] operands; returns (dq, dk, dv).
    g_lse: optional lane-broadcast [BH, T, 128] cotangent of the lse output
    (only flash_attention_with_lse callers have one)."""
    bh, t, d = q.shape
    tk = k.shape[1]
    sm_scale = 1.0 / (d**0.5)
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    _check_pltpu()

    has_glse = g_lse is not None
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec_q = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    lse_spec_q, _ = _lse_out(bh, t, block_q, lambda b, i, j: (b, i, 0))

    dq_in_specs = [q_spec, kv_spec_q, kv_spec_q, q_spec, q_spec, lse_spec_q]
    dq_operands = [q, k, v, o, do, lse]
    if has_glse:
        dq_in_specs.append(lse_spec_q)
        dq_operands.append(g_lse)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_k=tk, has_glse=has_glse,
        ),
        grid=(bh, pl.cdiv(t, block_q), pl.cdiv(tk, block_k)),
        in_specs=dq_in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(*dq_operands)

    # dK/dV: k-blocks parallel, q-blocks sequential (block index roles swap).
    q_spec_k = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec_k = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    lse_spec_k, _ = _lse_out(bh, t, block_q, lambda b, j, i: (b, i, 0))
    dkv_in_specs = [q_spec_k, kv_spec_k, kv_spec_k, q_spec_k, q_spec_k, lse_spec_k]
    dkv_operands = [q, k, v, o, do, lse]
    if has_glse:
        dkv_in_specs.append(lse_spec_k)
        dkv_operands.append(g_lse)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, seq_q=t, seq_k=tk,
            has_glse=has_glse,
        ),
        grid=(bh, pl.cdiv(tk, block_k), pl.cdiv(t, block_q)),
        in_specs=dkv_in_specs,
        out_specs=[kv_spec_k, kv_spec_k],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **kwargs,
    )(*dkv_operands)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention_pallas(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """[B, H, T, D] fused attention; differentiable (fused pallas backward).
    The primal (eval/inference) skips the lse residual entirely."""
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    o, _ = _flash_fwd(
        flat(q), flat(k), flat(v), causal, block_q, block_k, interpret,
        save_residuals=False,
    )
    return o.reshape(b, h, t, d)


def _fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name

    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    o, lse = _flash_fwd(
        flat(q), flat(k), flat(v), causal, block_q, block_k, interpret
    )
    # Named for selective remat (TransformerConfig.remat_save_flash ->
    # save_only_these_names policy): a
    # rematted backward that saves (o, lse) — ~100 MB/layer at 64k vs the
    # O(T^2) flash fwd replay — skips recomputing the quadratic kernel
    # entirely; only the cheap linear ops replay. Tags are inert without a
    # matching policy (default remat still recomputes everything).
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o.reshape(b, h, t, d), (q, k, v, o, lse)


def _bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, o_flat, lse = res
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    dq, dk, dv = _flash_bwd(
        flat(q), flat(k), flat(v), o_flat, lse, flat(g),
        causal, block_q, block_k, interpret,
    )
    unflat = lambda x: x.reshape(b, h, x.shape[1], d)  # noqa: E731
    return unflat(dq), unflat(dk), unflat(dv)


flash_attention_pallas.defvjp(_fwd_rule, _bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False, block_q: int = 1024, block_k: int = 1024,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """[B, H, T, D] fused attention returning (o, lse [B, H, T] f32).

    For combiners that merge partial attention results by logsumexp weights
    (ring attention's per-device blocks): the lse output is differentiable —
    its cotangent enters the backward kernels as the dlse/dS = P term."""
    return _fwd_lse_rule(q, k, v, causal, block_q, block_k, interpret)[0]


def _fwd_lse_rule(q, k, v, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    o, lse3 = _flash_fwd(
        flat(q), flat(k), flat(v), causal, block_q, block_k, interpret,
        save_residuals=True,
    )
    lse_flat = _lse_flat(lse3)
    out = (o.reshape(b, h, t, d), lse_flat.reshape(b, h, t))
    return out, (q, k, v, o, lse3)


def _bwd_lse_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, o_flat, lse3 = res
    g_o, g_lse = g
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    g_lse3 = _lse_store(g_lse.reshape(b * h, t).astype(jnp.float32), t)
    dq, dk, dv = _flash_bwd(
        flat(q), flat(k), flat(v), o_flat, lse3, flat(g_o),
        causal, block_q, block_k, interpret, g_lse=g_lse3,
    )
    unflat = lambda x: x.reshape(b, h, x.shape[1], d)  # noqa: E731
    return unflat(dq), unflat(dk), unflat(dv)


flash_attention_with_lse.defvjp(_fwd_lse_rule, _bwd_lse_rule)
