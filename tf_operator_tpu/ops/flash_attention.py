"""Fused flash attention (forward) as a pallas TPU kernel.

Why a kernel at all: naive attention materialises the [T, T] score matrix in
HBM — O(T^2) bytes against HBM bandwidth, the usual TPU bottleneck. This
kernel streams K/V blocks through VMEM and keeps the online-softmax
accumulator (m, l, acc) in VMEM scratch across the innermost grid dimension,
so HBM traffic is O(T*D) and the two matmuls per block hit the MXU back to
back (FlashAttention recurrence; kernel structure per the pallas TPU guide:
3D grid (batch*heads, q-blocks, k-blocks) with the k dimension "arbitrary"
= sequential, accumulating into scratch, output written on the last k step).

Block sizes default to 128x128 (MXU-native); causal masking prunes whole
K-blocks above the diagonal with pl.when, halving work for causal LMs.

Backward pass: flash_attention is wrapped in jax.custom_vjp whose backward
recomputes attention blockwise in plain JAX (O(T) memory via jax.checkpoint-
style recompute); a fused pallas backward is future work.

Use ops.attention.flash_attention — it dispatches pallas-on-TPU / reference
elsewhere. `interpret=True` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas bits are absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, sm_scale: float, causal: bool, block_q: int, block_k: int, seq_k: int,
):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    last_k = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]  # (BQ, D)
        k = k_ref[0]  # (BK, D)
        v = v_ref[0]  # (BK, D)
        # Zero padded tail rows of V: p is 0 there, but 0 * <pad garbage>
        # would still poison the accumulator (0*NaN=NaN).
        v_row = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(v_row < seq_k, v, 0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # (BQ, BK)

        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < seq_k  # mask the zero-padded tail of the last K-block
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where((m_new == NEG_INF)[:, None], 0.0, p)  # fully-masked rows
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev == NEG_INF, 0.0, alpha)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    if causal:
        # Skip K-blocks entirely above the diagonal.
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == last_k)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)


def _flash_fwd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool, block_q: int, block_k: int, interpret: bool,
) -> jax.Array:
    """q,k,v: [BH, T, D] (batch*heads flattened)."""
    bh, t, d = q.shape
    tk = k.shape[1]
    sm_scale = 1.0 / (d**0.5)
    block_q = min(block_q, t)
    block_k = min(block_k, tk)
    grid = (bh, pl.cdiv(t, block_q), pl.cdiv(tk, block_k))

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_k=tk,
    )
    kwargs = {}
    if _HAS_PLTPU and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU backend unavailable; use ops.attention.flash_attention "
            "which falls back to the reference implementation"
        )
    scratch = [
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, 128), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **kwargs,
    )(q, k, v)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6)
)
def flash_attention_pallas(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False, block_q: int = 128, block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """[B, H, T, D] fused attention; differentiable (recompute backward)."""
    b, h, t, d = q.shape
    flat = lambda x: x.reshape(b * h, x.shape[2], d)  # noqa: E731
    o = _flash_fwd(flat(q), flat(k), flat(v), causal, block_q, block_k, interpret)
    return o.reshape(b, h, t, d)


def _fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    o = flash_attention_pallas(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v)


def _bwd_rule(causal, block_q, block_k, interpret, res, g):
    """Recompute-based backward: differentiate the reference implementation
    (memory O(T^2) only for the local shard; a fused pallas bwd is future
    work — numerics are exact either way)."""
    from tf_operator_tpu.parallel.ring_attention import attention_reference

    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention_reference(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention_pallas.defvjp(_fwd_rule, _bwd_rule)
