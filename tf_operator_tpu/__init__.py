"""tf_operator_tpu — a TPU-native distributed training-job operator framework.

A ground-up rebuild of the capabilities of the kubeflow/tf-operator (hudson741
fork, reference at /root/reference): a declarative TrainJob API, a reconciling
controller that materialises replica pods + stable DNS identity, cluster-spec
injection (TF_CONFIG parity and a TPU/JAX-native contract), a condition state
machine, gang scheduling mapped onto atomic TPU-slice acquisition, lifecycle
policies (restart/backoff/deadline/TTL/cleanup), plus a JAX/XLA data plane
(models, pallas-ready ops, SPMD parallelism over device meshes) that the
reference delegated to user containers.

Layer map (mirrors SURVEY.md §1, re-targeted TPU-first):

  api/           TrainJob spec types, defaulting, validation       (ref pkg/apis)
  core/          cluster substrate, workqueue, expectations,
                 generic job controller + TrainJob controller      (ref pkg/common/jobcontroller,
                                                                    pkg/controller.v1/tensorflow)
  cluster_spec/  TF_CONFIG + TPU/JAX distributed env injection     (ref tensorflow.go)
  status/        replica counts -> job condition state machine     (ref status.go)
  gang/          TPU slice topology model + PodGroup gang sched    (ref jobcontroller.go:226)
  runtime/       executors: local-process runtime, native C++ core
  testing/       fake workload server + builders                   (ref test/test-server, testutil)
  models/        JAX/flax model zoo (MNIST, ResNet-50, Transformer)
  ops/           TPU kernels (pallas) with portable fallbacks
  parallel/      mesh construction, dp/tp/sp/pp shardings, ring attention
  utils/         naming, env, exit codes, structured logging
  cli/           operator entrypoint, metrics, leader election     (ref cmd/tf-operator.v1)

The control plane (api/core/cluster_spec/status/gang/utils/cli) imports no JAX:
it can run on any host. JAX appears only in the data plane (models/ops/parallel)
and in workload processes the runtime spawns.
"""

__version__ = "0.1.0"

from tf_operator_tpu.api.types import (  # noqa: F401
    CleanPodPolicy,
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TrainJob,
    TrainJobSpec,
)
