"""LocalSession: a single-host, fully-running instance of the framework.

Wires together the cluster substrate, the TrainJob controller (threaded), and
the local-process runtime, and exposes the client-side verbs the reference's
E2E harness built on (py/kubeflow/tf_operator/tf_job_client.py):

  submit / wait_for_condition / wait_for_delete / delete
  terminate_replica (the /exit fault-injection hook, tf_job_client.py:302-352)
  replica_address  (reach a replica's HTTP surface through the port map)

This is what `tpujob run job.yaml` and bench.py drive; E2E tests use it to
reproduce the reference's eight behavior suites on one machine.
"""

from __future__ import annotations

import json
import socket
import time
import urllib.request

from tf_operator_tpu.api.types import JobConditionType, TrainJob
from tf_operator_tpu.core.cluster import InMemoryCluster
from tf_operator_tpu.core.trainjob_controller import TrainJobController
from tf_operator_tpu.gang.podgroup import SliceAllocator
from tf_operator_tpu.runtime.local import LocalProcessRuntime
from tf_operator_tpu.utils.naming import gen_general_name


class TimeoutError_(TimeoutError):
    pass


class LocalSession:
    def __init__(
        self,
        enable_gang: bool = False,
        slice_allocator: SliceAllocator | None = None,
        workers: int = 2,
        env_overrides: dict[str, str] | None = None,
        log_dir: str | None = None,
        scheduler=None,
    ):
        self.cluster = InMemoryCluster()
        # With a log_dir the runtime injects per-pod heartbeat/metrics
        # files; the collector reads them back as the controller's
        # heartbeat source (hang watchdog + consecutive-restart reset).
        self.telemetry = None
        if log_dir:
            from tf_operator_tpu.telemetry.collector import TelemetryCollector

            self.telemetry = TelemetryCollector(log_dir)
        # scheduler (sched.FleetScheduler): priority/quota/fair-share
        # admission + graceful preemption over the slice fleet.
        self.scheduler = scheduler
        # Cross-kind enqueue routing: TrainJob and InferenceService share
        # the scheduler/allocator, so a freed slice's kick targets (and
        # preemption victims) may belong to either controller — one
        # shared router definition (core.controller.make_enqueue_router).
        from tf_operator_tpu.core.controller import make_enqueue_router

        train_ref: list = []
        serve_ref: list = []
        _route = make_enqueue_router(train_ref, serve_ref)

        self.controller = TrainJobController(
            self.cluster, enable_gang=enable_gang,
            slice_allocator=slice_allocator,
            heartbeat_source=self.telemetry,
            scheduler=scheduler,
            enqueue_router=_route,
        )
        train_ref.append(self.controller)
        # The second workload kind, through the same generic base +
        # shared capacity plane (serve/controller.py).
        from tf_operator_tpu.serve.controller import (
            InferenceServiceController,
        )

        # The runtime comes up before the serve controller so the
        # front-end router's backends can resolve through its port map.
        self.runtime = LocalProcessRuntime(
            self.cluster, env_overrides=env_overrides, log_dir=log_dir
        )
        from tf_operator_tpu.serve.router import local_endpoint_resolver

        self.serve_controller = InferenceServiceController(
            self.cluster,
            slice_allocator=slice_allocator,
            scheduler=scheduler,
            heartbeat_source=self.telemetry,
            enqueue_router=_route,
            endpoint_resolver=local_endpoint_resolver(self.runtime),
        )
        serve_ref.append(self.serve_controller)
        # Round-robin cursor per service for service_address (the
        # client side of the router tier).
        self._service_rr: dict[tuple[str, str], int] = {}
        self.controller.run(workers=workers)
        self.serve_controller.run(workers=1)

    # ------------------------------------------------------------- client API

    def submit(self, job: TrainJob) -> TrainJob:
        return self.cluster.create_job(job)

    def get(self, namespace: str, name: str) -> TrainJob | None:
        return self.cluster.try_get_job(namespace, name)

    def delete(self, namespace: str, name: str) -> None:
        self.cluster.delete_job(namespace, name)

    def wait_for_condition(
        self,
        namespace: str,
        name: str,
        conditions: tuple[JobConditionType, ...],
        timeout: float = 60.0,
        poll: float = 0.05,
    ) -> TrainJob:
        """Block until the job has any of `conditions` with status=True
        (tf_job_client.wait_for_condition:117)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            job = self.cluster.try_get_job(namespace, name)
            if job is not None:
                for c in job.status.conditions:
                    if c.status and c.type in conditions:
                        return job
            time.sleep(poll)
        raise TimeoutError_(
            f"job {namespace}/{name} did not reach {[str(c) for c in conditions]} "
            f"within {timeout}s"
        )

    # ------------------------------------------------- InferenceService API

    def submit_service(self, svc):
        return self.cluster.create_infsvc(svc)

    def get_service(self, namespace: str, name: str):
        return self.cluster.try_get_infsvc(namespace, name)

    def delete_service(self, namespace: str, name: str) -> None:
        self.cluster.delete_infsvc(namespace, name)

    def wait_for_service_condition(
        self,
        namespace: str,
        name: str,
        conditions: tuple[JobConditionType, ...],
        timeout: float = 60.0,
        poll: float = 0.05,
    ):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            svc = self.cluster.try_get_infsvc(namespace, name)
            if svc is not None:
                for c in svc.status.conditions:
                    if c.status and c.type in conditions:
                        return svc
            time.sleep(poll)
        raise TimeoutError_(
            f"service {namespace}/{name} did not reach "
            f"{[str(c) for c in conditions]} within {timeout}s"
        )

    def server_address(self, service: str, namespace: str, index: int,
                       port: int = 8500) -> str | None:
        """127.0.0.1:port address of one serving replica (the serve-port
        localhost rewrite, same port-map contract as replica_address)."""
        return self.replica_address(service, namespace, "server", index,
                                    port=port)

    def service_addresses(self, service: str,
                          namespace: str = "default") -> list[str]:
        """Every router in the service's front-end tier, slot-ordered
        (status.routerEndpoints; falls back to the legacy singular for
        pre-tier statuses). Empty until the first reconcile publishes
        them."""
        svc = self.cluster.try_get_infsvc(namespace, service)
        if svc is None:
            return []
        eps = list(svc.status.router_endpoints)
        if not eps and svc.status.router_endpoint:
            eps = [svc.status.router_endpoint]
        return eps

    def service_address(self, service: str,
                        namespace: str = "default") -> str | None:
        """ONE address of the service's front-end router tier
        (serve/router.py): least-loaded + readiness-gated routing over
        the replicas — what clients should hit instead of per-replica
        round-robin. Round-robins across the tier's endpoints and fails
        over past a dead one: each candidate gets a cheap connect probe,
        so a router killed between reconciles costs the NEXT sibling's
        address, not 111s against a cached dead port until the
        controller replaces it. None until the first reconcile
        publishes an endpoint."""
        eps = self.service_addresses(service, namespace)
        if not eps:
            return None
        start = self._service_rr.get((namespace, service), 0)
        self._service_rr[(namespace, service)] = start + 1
        for i in range(len(eps)):
            addr = eps[(start + i) % len(eps)]
            host, _, port = addr.rpartition(":")
            try:
                # Connect-phase only: a live listener accepts instantly.
                # A refused/timed-out connect means a dead router —
                # skip to the next sibling (client-seam failover).
                socket.create_connection((host, int(port)),
                                         timeout=0.25).close()
            except OSError:
                continue
            return addr
        # Nobody accepted (all routers mid-replacement): hand back the
        # round-robin choice — the caller's own retry loop covers the
        # gap, and hiding the address entirely would read as "service
        # never came up".
        return eps[start % len(eps)]

    def kill_router(self, service: str, namespace: str = "default",
                    index: int = 0) -> str | None:
        """Fault injection: close ONE router of the service's front-end
        tier (its port goes dead like a crashed router process; the
        shared backend table and the siblings keep serving). The serve
        controller replaces it on its next tick — this is what the
        mid-ramp router-kill gate drives. Returns the dead endpoint, or
        None when there is no such router."""
        tier = self.serve_controller._routers.get(f"{namespace}/{service}")
        if tier is None:
            return None
        dead = tier.kill(index)
        if dead is not None:
            # The controller replaces the dead listener on its next
            # reconcile — kick one rather than waiting for the resync.
            self.serve_controller.enqueue(f"{namespace}/{service}")
        return dead

    def timeline(self, namespace: str, name: str) -> dict | None:
        """The flight-recorder timeline for one job — the same payload
        the operator serves at /api/trainjobs/{ns}/{name}/timeline
        (journaled events + phase breakdown + trainer telemetry)."""
        from tf_operator_tpu.telemetry import journal as journal_lib

        return journal_lib.timeline_payload(
            namespace, name, telemetry=self.telemetry)

    def wait_for_delete(self, namespace: str, name: str, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.cluster.try_get_job(namespace, name) is None:
                return
            time.sleep(0.05)
        raise TimeoutError_(f"job {namespace}/{name} not deleted within {timeout}s")

    # -------------------------------------------------- fault injection / HTTP

    def replica_address(
        self, job_name: str, namespace: str, rtype: str, index: int, port: int = 2222
    ) -> str | None:
        """127.0.0.1:port HTTP address of a replica's workload server
        (`port` is the declared containerPort, default tfjob-port 2222)."""
        pm = self.runtime.port_map(job_name, namespace)
        if pm is None:
            return None
        host = f"{gen_general_name(job_name, rtype, index)}.{namespace}.svc"
        return pm.local_addr(host, port)

    def replica_http(self, job_name: str, namespace: str, rtype: str, index: int,
                     path: str, timeout: float = 5.0) -> dict:
        addr = self.replica_address(job_name, namespace, rtype, index)
        if addr is None:
            raise RuntimeError(f"no address for {job_name} {rtype}-{index}")
        with urllib.request.urlopen(f"http://{addr}{path}", timeout=timeout) as r:
            return json.loads(r.read())

    def terminate_replica(
        self, job_name: str, namespace: str, rtype: str, index: int, exit_code: int = 0
    ) -> dict:
        """Force a replica to exit with a chosen code via the workload's
        /exit endpoint (tf_job_client.terminate_replicas:317)."""
        return self.replica_http(
            job_name, namespace, rtype, index, f"/exit?exitCode={exit_code}"
        )

    def wait_replica_serving(
        self, job_name: str, namespace: str, rtype: str, index: int, timeout: float = 20.0
    ) -> None:
        deadline = time.monotonic() + timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                self.replica_http(job_name, namespace, rtype, index, "/health", timeout=1.0)
                return
            except Exception as e:
                last = e
                time.sleep(0.1)
        raise TimeoutError_(
            f"replica {rtype}-{index} of {job_name} never served /health: {last}"
        )

    # ------------------------------------------------------------------ stop

    def prewarm(self, timeout: float = 30.0) -> bool:
        """Wait for the runtime's prespawn fork server (deploy-time warmup;
        jobs submitted after this start their pods pre-imported)."""
        return self.runtime.prewarm(timeout)

    def close(self) -> None:
        self.runtime.stop()
        self.controller.stop()
        self.serve_controller.stop()

    def __enter__(self) -> "LocalSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
