"""Executors: materialise pods as real processes; native C++ runtime core."""
