"""Local-process runtime: run a cluster's pods as OS processes on this host.

The reference's data plane was "kubelet starts the `tensorflow` container"
(SURVEY.md §3.3); the operator never executed anything itself. This runtime is
the kubelet stand-in for a single host: it watches pod creations on the
cluster substrate, spawns one subprocess per pod, feeds phase transitions and
exit codes back into pod status (which drives the controller's state machine
exactly as container statuses did, pod.go:135-162), and emulates kubelet
restart policy (Always/OnFailure restart the process in place and bump
restart_count — the counts pastBackoffLimit sums).

Networking: the injected cluster spec uses in-cluster DNS names
(`{job}-{type}-{i}.{ns}.svc:2222`). Those don't resolve on a laptop/CI host,
so the runtime allocates per-replica localhost ports and rewrites every env
value (TF_CONFIG JSON, JAX_COORDINATOR_ADDRESS, TPU_WORKER_HOSTNAMES,
KUBE_GOOGLE_CLOUD_TPU_ENDPOINTS) from DNS identity to 127.0.0.1:port. Real
multi-process jax.distributed / TF gRPC meshes then form locally — the same
contract a multi-host deployment gets from headless services, scaled down to
one machine.
"""

from __future__ import annotations

import os
import re
import signal
import socket
import subprocess
import threading
import time
from dataclasses import dataclass, field

from tf_operator_tpu.core.cluster import (
    ENDPOINT_ANNOTATION,
    KIND_POD,
    ContainerStatus,
    NotFoundError,
    Pod,
    PodPhase,
)
from tf_operator_tpu.status import metrics as status_metrics
from tf_operator_tpu.utils.logging import logger_for_pod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _PopenProcess:
    """Popen-backed handle with the NativeProcess interface: process-group
    signals, signal deaths normalized to 128+sig exit codes."""

    def __init__(self, popen: subprocess.Popen):
        self._p = popen
        self.pid = popen.pid

    @staticmethod
    def _norm(code: int | None) -> int | None:
        return 128 - code if code is not None and code < 0 else code

    def poll(self) -> int | None:
        return self._norm(self._p.poll())

    def wait(self, timeout: float | None = None) -> int:
        try:
            return self._norm(self._p.wait(timeout))
        except subprocess.TimeoutExpired as e:
            raise TimeoutError(str(e)) from None

    def _signal(self, sig: int) -> None:
        try:
            os.killpg(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            try:
                self._p.send_signal(sig)
            except ProcessLookupError:
                pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def release(self) -> None:
        pass


class _PopenSupervisor:
    def spawn(self, cmd, env=None, cwd=None, logfile=None) -> _PopenProcess:
        stdout = subprocess.DEVNULL
        if logfile:
            stdout = open(logfile, "ab")
        try:
            p = subprocess.Popen(
                cmd,
                env=env,
                stdout=stdout,
                stderr=subprocess.STDOUT,
                cwd=cwd or None,
                start_new_session=True,
            )
        finally:
            if stdout is not subprocess.DEVNULL:
                stdout.close()
        return _PopenProcess(p)


def make_supervisor():
    """Native (C++) supervisor when the library is available, else Popen."""
    try:
        from tf_operator_tpu.native import NativeSupervisor

        return NativeSupervisor()
    except (ImportError, RuntimeError):
        return _PopenSupervisor()


@dataclass
class _Proc:
    pod_uid: str
    process: object  # NativeProcess | _PopenProcess
    restart_count: int = 0
    stopping: bool = False


@dataclass
class PortMap:
    """Per-job mapping: (DNS host, declared port) -> unique localhost port.

    Ports are whatever the manifest declared (default 2222/8476, but any
    containerPort works): every distinct host:port endpoint gets its own
    localhost port so replicas never collide on one machine."""

    ports: dict[str, dict[int, int]] = field(default_factory=dict)

    def local_port(self, host: str, port: int) -> int | None:
        return self.ports.get(host, {}).get(port)

    def local_addr(self, host_prefix: str, port: int) -> str | None:
        """'127.0.0.1:p' for the first host matching `host_prefix` (a
        replica's DNS identity, sans port), preferring the declared
        `port` and falling back to the host's lowest mapped port. The
        ONE lookup both LocalSession.replica_address and the front-end
        router's endpoint resolver share — the two consumers must never
        drift on the prefix/fallback rules."""
        for h, mapping in self.ports.items():
            if h.startswith(host_prefix):
                local = mapping.get(port)
                if local is None and mapping:
                    local = sorted(mapping.values())[0]
                return (f"127.0.0.1:{local}" if local is not None
                        else None)
        return None

    def rewrite(self, value: str) -> str:
        # host:port pairs first (longest match), then bare hostnames.
        for host, mapping in self.ports.items():
            for port, local in mapping.items():
                value = value.replace(f"{host}:{port}", f"127.0.0.1:{local}")
        for host in self.ports:
            value = value.replace(host, "127.0.0.1")
        return value


class LocalProcessRuntime:
    """Kubelet stand-in: one subprocess per pod, status fed back to the
    cluster substrate."""

    def __init__(
        self,
        cluster,  # InMemoryCluster | core.k8s.K8sCluster (same surface)
        env_overrides: dict[str, str] | None = None,
        inherit_env: bool = True,
        log_dir: str | None = None,
        external_scheduler: bool = False,
    ):
        self.cluster = cluster
        self.env_overrides = env_overrides or {}
        self.inherit_env = inherit_env
        self.log_dir = log_dir
        self._procs: dict[tuple[str, str], _Proc] = {}
        self._draining: dict[tuple[str, str], object] = {}
        self._supervisor = make_supervisor()
        # Pre-warmed fork server: cuts the ~4 s Python/JAX import tax off
        # every `python -m` pod (runtime/prespawn.py). Started here so it
        # warms during operator startup; pods fall back to a normal spawn
        # until it is ready. TPUJOB_PRESPAWN=0 disables.
        if os.environ.get("TPUJOB_PRESPAWN", "1") != "0":
            try:
                import tempfile

                from tf_operator_tpu.runtime.prespawn import PrespawnSupervisor

                sock = os.path.join(
                    tempfile.gettempdir(), f"tpujob-ps-{os.getpid()}-{id(self):x}"
                )
                self._supervisor = PrespawnSupervisor(self._supervisor, sock)
            except Exception:
                pass  # optimization only; the base supervisor always works
        self._port_maps: dict[tuple[str, str], PortMap] = {}  # (ns, job) -> map
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._started: set[tuple[str, str]] = set()
        self._stopped = False
        cluster.on_add(KIND_POD, self._on_pod_add)
        cluster.on_delete(KIND_POD, self._on_pod_delete)
        # Gang-scheduler conformance mode (VERDICT r3 next #7): when an
        # external gang scheduler owns placement, this kubelet behaves like
        # a real one — a pod naming a foreign schedulerName stays Pending
        # (never executed) until that scheduler BINDS it (sets
        # spec.nodeName). Default off: the local runtime otherwise plays
        # scheduler+kubelet in one, starting pods on creation.
        self.external_scheduler = external_scheduler
        if external_scheduler:
            cluster.on_update(KIND_POD, self._on_pod_update)

    # ----------------------------------------------------------- port wiring

    _HOSTPORT_RE = re.compile(
        r"([a-z0-9.-]+\.svc(?:\.[a-z0-9.-]+)?):(\d+)"
    )

    def _port_map_for(self, pod: Pod) -> PortMap:
        """Build (incrementally, per job) the DNS->localhost port map from
        every `host.svc[:port]` endpoint the pod's env mentions (TF_CONFIG
        JSON, coordinator address, TPU endpoints, worker hostnames)."""
        job_key = (pod.namespace, pod.metadata.labels.get("job-name", ""))
        with self._lock:
            pm = self._port_maps.get(job_key)
            if pm is None:
                pm = PortMap()
                self._port_maps[job_key] = pm
            endpoints: set[tuple[str, int]] = set()
            bare_hosts: set[str] = set()
            for c in pod.spec.containers:
                declared = [p.container_port for p in c.ports if p.container_port]
                for e in c.env:
                    for host, port in self._HOSTPORT_RE.findall(e.value):
                        endpoints.add((host, int(port)))
                    # Bare hostnames (TPU_WORKER_HOSTNAMES): give them every
                    # port their container declares.
                    for token in e.value.replace(",", " ").split():
                        t = token.strip('"')
                        if (t.endswith(".svc") or ".svc." in t) and ":" not in t:
                            bare_hosts.add(t)
                for h in bare_hosts:
                    for port in declared:
                        endpoints.add((h, port))
            for host, port in endpoints:
                pm.ports.setdefault(host, {})
                if port not in pm.ports[host]:
                    pm.ports[host][port] = _free_port()
            return pm

    # ------------------------------------------------------------- lifecycle

    def _awaits_binding(self, pod: Pod) -> bool:
        """True when an external gang scheduler owns this pod's placement
        and has not bound it yet (volcano protocol: the operator creates
        the whole gang with schedulerName + group annotation; pods run only
        after the scheduler binds them — jobcontroller.go:226-250)."""
        scheduler = pod.scheduler_name or pod.spec.scheduler_name
        return bool(self.external_scheduler and scheduler and not pod.node_name)

    def _on_pod_add(self, pod: Pod) -> None:
        if self._stopped:
            return
        if self._awaits_binding(pod):
            return  # Pending until the gang scheduler binds it
        self._launch(pod)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if self._stopped:
            return
        if new.node_name and not self._awaits_binding(new):
            self._launch(new)  # just bound (no-op if already started)

    def _launch(self, pod: Pod) -> None:
        key = (pod.namespace, pod.name)
        with self._lock:
            if key in self._started:
                return  # updates replay; a pod executes once per creation
            self._started.add(key)
            t = threading.Thread(
                target=self._run_pod, args=(pod,), name=f"pod-{pod.name}",
                daemon=True,
            )
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()

    def _on_pod_delete(self, pod: Pod) -> None:
        with self._lock:
            # A recreated pod (ExitCode restart, elastic roll) is a new
            # execution: forget the old one's started mark.
            self._started.discard((pod.namespace, pod.name))
            # Opportunistic purge: entries whose process already exited are
            # dead weight (a job deleted mid-run with no successor would
            # otherwise pin its handles for the runtime's lifetime).
            for key in [k for k, (_, p) in self._draining.items()
                        if p.poll() is not None]:
                del self._draining[key]
            proc = self._procs.pop((pod.namespace, pod.name), None)
            if proc is not None:
                # Track the dying process: replacement pods of the SAME JOB
                # (elastic roll, ExitCode recreate) must not start while any
                # old-generation process still runs — a new jax.distributed
                # worker dialing the OLD generation's still-alive coordinator
                # aborts the whole gang ("unexpected incarnation"), and a
                # SIGTERM'd process can linger seconds inside a collective
                # before its handler runs.
                job = pod.metadata.labels.get("job-name", "")
                self._draining[(pod.namespace, pod.name)] = (job, proc.process)
        if proc is not None:
            proc.stopping = True
            self._terminate(proc.process)

            def _drop_after_exit(p=proc.process, uid=pod.metadata.uid):
                # _terminate only sends SIGTERM: the trainer latches it,
                # finishes the in-flight step, and writes a final heartbeat
                # at the boundary — recreating the file AFTER the unlink
                # below. For a pod that is never respawned (scale-down, job
                # deleted) that resurrected file would be exactly the stale
                # signal the drop exists to prevent, so drop again once the
                # process is confirmed dead. Skip if a replacement pod
                # already exists — its spawn-side drop owns the file now,
                # and unlinking here would blip its live heartbeat.
                try:
                    p.wait(timeout=60.0)
                except Exception:
                    pass
                cur = self.cluster.try_get_pod(pod.namespace, pod.name)
                if cur is None or cur.metadata.uid == uid:
                    self._drop_heartbeat(pod)

            threading.Thread(target=_drop_after_exit, daemon=True,
                             name=f"hb-drop-{pod.name}").start()
        self._drop_heartbeat(pod)

    def _drop_heartbeat(self, pod: Pod) -> None:
        """The heartbeat drives control decisions (hang watchdog, restart
        tally reset), so a deleted or replaced pod must not leave a stale
        file behind: the collector aggregates by job-name glob, and a
        resubmitted same-name job (or one scaled below its old replica
        count) would inherit the dead run's step high-water and heartbeat
        existence. Only the runtime-injected per-pod default path is
        dropped — an explicit TPUJOB_HEARTBEAT_FILE override is the
        caller's to manage, and metrics event files deliberately persist
        (they are the append-only post-mortem record)."""
        if not self.log_dir:
            return
        for suffix in ("heartbeat.json", "serve.json"):
            # serve.json rides the same rule: a deleted replica's stale
            # inflight snapshot would keep inflating the autoscaler's
            # load sum (the controller also filters by live pods — this
            # is the belt to that suspender).
            try:
                os.unlink(os.path.join(
                    self.log_dir,
                    f"{pod.namespace}_{pod.name}.{suffix}"
                ))
            except OSError:
                pass

    def _await_drained(self, ns: str, job: str, grace: float = 5.0,
                       timeout: float = 12.0) -> None:
        """Block until every draining process of (ns, job) is dead (SIGKILL
        after `grace`), so a new generation can bind the old one's ports.

        The grace is the local analogue of the kubelet's
        terminationGracePeriodSeconds: a SIGTERM'd trainer that cannot
        reach a step boundary (wedged in a collective against a dead
        peer) still has an independent async checkpoint writer finishing
        its in-flight save — 2 s (the pre-round-15 value) raced that
        write's tail on a loaded host and SIGKILLed mid-publish what a
        real cluster (30 s default grace) would let land. Only WEDGED
        processes ever pay the full grace; a trainer that latches the
        SIGTERM at a boundary exits in milliseconds."""
        with self._lock:
            priors = [
                (key, p) for key, (j, p) in self._draining.items()
                if key[0] == ns and j == job
            ]
        if not priors:
            return
        start = time.time()
        deadline = start + timeout
        killed = False
        while time.time() < deadline:
            if all(p.poll() is not None for _, p in priors):
                break
            if not killed and time.time() - start > grace:
                for _, p in priors:
                    if p.poll() is None:
                        try:
                            p.kill()
                        except ProcessLookupError:
                            pass
                killed = True
            time.sleep(0.02)
        with self._lock:
            for key, p in priors:
                if (self._draining.get(key) or (None, None))[1] is p:
                    del self._draining[key]

    @staticmethod
    def _terminate(process) -> None:
        if process.poll() is None:
            process.terminate()

    def _build_env(self, pod: Pod, pm: PortMap) -> dict[str, str]:
        env = dict(os.environ) if self.inherit_env else {}
        container = pod.spec.containers[0]
        for e in container.env:
            env[e.name] = pm.rewrite(e.value)
        # This replica's own listen ports: the localhost ports its DNS
        # identity was rewritten to, keyed by the container's declared ports.
        own_host, port_by_name = self._own_host(pod, pm)
        if own_host is not None:
            tf_local = pm.local_port(own_host, port_by_name.get("tfjob-port", 2222))
            coord_local = pm.local_port(own_host, port_by_name.get("coord-port", 8476))
            if tf_local is not None:
                env["TPUJOB_LISTEN_PORT"] = str(tf_local)
            if coord_local is not None:
                env["TPUJOB_COORD_LISTEN_PORT"] = str(coord_local)
            # Serving replicas (serve/server.py): the localhost port the
            # replica's serve-port DNS identity was rewritten to.
            serve_local = pm.local_port(
                own_host, port_by_name.get("serve-port", 8500))
            if serve_local is not None:
                env["TPUJOB_SERVE_LISTEN_PORT"] = str(serve_local)
        env.update(self.env_overrides)
        # Per-pod trainer event file beside the pod's log: the operator's
        # telemetry collector reads it back into the job's API `telemetry`
        # block and the labeled tpujob_trainer_* gauges. Anything already
        # set (bench/tests via env_overrides, an inherited env) wins — the
        # runtime only fills the gap.
        if self.log_dir and not env.get("TPUJOB_METRICS_FILE"):
            env["TPUJOB_METRICS_FILE"] = os.path.join(
                self.log_dir, f"{pod.namespace}_{pod.name}.metrics.jsonl"
            )
        # Progress heartbeat (round 10, same pattern as the metrics file):
        # the trainer os.replace's a tiny {step, t} JSON here at step
        # boundaries; the controller's hang watchdog and the telemetry
        # collector's tpujob_heartbeat_age_seconds gauge read it back.
        if self.log_dir and not env.get("TPUJOB_HEARTBEAT_FILE"):
            env["TPUJOB_HEARTBEAT_FILE"] = os.path.join(
                self.log_dir, f"{pod.namespace}_{pod.name}.heartbeat.json"
            )
        # Serve stats (serve/server.py, same pattern): the server
        # os.replace's its {inflight, latency} snapshot here; the
        # collector reads it back as the autoscaler's load signal.
        # Trainers simply never write it.
        if self.log_dir and not env.get("TPUJOB_SERVE_STATS_FILE"):
            env["TPUJOB_SERVE_STATS_FILE"] = os.path.join(
                self.log_dir, f"{pod.namespace}_{pod.name}.serve.json"
            )
        # Multi-slice DCN rendezvous (parallel/multislice.py): one shared
        # directory per JOB INSTANCE — the operator-injected epoch token
        # (job uid) keeps a resubmitted same-name job from inheriting a
        # dead run's exchange files. A real cluster points this at a
        # shared volume instead.
        if (self.log_dir and not env.get("TPUJOB_DCN_DIR")
                and env.get("TPUJOB_NUM_SLICES", "1") not in ("", "0", "1")):
            job = pod.metadata.labels.get("job-name", "")
            epoch = env.get("TPUJOB_DCN_EPOCH", "0")
            env["TPUJOB_DCN_DIR"] = os.path.join(
                self.log_dir, f"{pod.namespace}_{job}.dcn-{epoch}"
            )
        return env

    def _own_host(self, pod: Pod, pm: PortMap) -> tuple[str | None, dict[str, int]]:
        """This replica's own DNS identity in the port map + its declared
        container ports by name (shared by env injection and the published
        endpoint so the listen port and the dialable address cannot drift)."""
        own = next((h for h in pm.ports if h.startswith(f"{pod.name}.")), None)
        ports = (
            {p.name: p.container_port for p in pod.spec.containers[0].ports}
            if pod.spec.containers else {}
        )
        return own, ports

    def _own_endpoint(self, pod: Pod, pm: PortMap) -> str | None:
        """This replica's tfjob-port as a dialable localhost address."""
        own_host, port_by_name = self._own_host(pod, pm)
        if own_host is None:
            return None
        local = pm.local_port(own_host, port_by_name.get("tfjob-port", 2222))
        if local is None:
            mapping = pm.ports.get(own_host) or {}
            local = sorted(mapping.values())[0] if mapping else None
        return f"127.0.0.1:{local}" if local is not None else None

    def _run_pod(self, pod: Pod) -> None:
        """Process lifecycle for one pod, including kubelet-style in-place
        restarts for Always/OnFailure pod restart policies."""
        log = logger_for_pod(pod.namespace, pod.name)
        if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            # A relist can replay pods that already ran to completion (e.g.
            # the node agent restarting against a live API server): never
            # re-execute them.
            return
        if not pod.spec.containers or not (
            pod.spec.containers[0].command or pod.spec.containers[0].args
        ):
            self.cluster.record_event(
                KIND_POD, pod.namespace, pod.name, "Warning", "NoCommand",
                "pod template has no command; the local-process runtime cannot "
                "pull container images — set spec.containers[].command",
            )
            self._set_status(pod, PodPhase.FAILED, None, 0, reason="NoCommand")
            return
        container = pod.spec.containers[0]
        cmd = list(container.command) + list(container.args)
        self._await_drained(
            pod.namespace, pod.metadata.labels.get("job-name", "")
        )
        # The pod may have been deleted while we waited (rapid successive
        # scale edits): spawning now would orphan a process that binds the
        # job's reused ports with no pod object tracking it.
        cur = self.cluster.try_get_pod(pod.namespace, pod.name)
        if self._stopped or cur is None or cur.metadata.uid != pod.metadata.uid:
            return
        # A fresh execution must not inherit a previous same-named pod's
        # heartbeat (runtime restarted over an old log_dir, job deleted
        # uncleanly): ordering after _await_drained means no old-generation
        # process can rewrite the file after this point.
        self._drop_heartbeat(pod)
        pm = self._port_map_for(pod)
        env = self._build_env(pod, pm)
        restart_policy = pod.spec.restart_policy or "Never"
        restart_count = 0

        while True:
            logfile = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                logfile = os.path.join(
                    self.log_dir, f"{pod.namespace}_{pod.name}.log"
                )
            try:
                process = self._supervisor.spawn(
                    cmd, env=env, cwd=container.working_dir or None, logfile=logfile
                )
            except OSError as e:
                log.error("spawn failed: %s", e)
                self._set_status(pod, PodPhase.FAILED, 127, restart_count, reason="SpawnError")
                return

            entry = _Proc(pod.metadata.uid, process, restart_count)
            with self._lock:
                self._procs[(pod.namespace, pod.name)] = entry
            self._set_status(pod, PodPhase.RUNNING, None, restart_count,
                             endpoint=self._own_endpoint(pod, pm))

            code = process.wait()
            process.release()
            if entry.stopping or self._stopped:
                return  # deleted: pod object is already gone

            should_restart = restart_policy == "Always" or (
                restart_policy == "OnFailure" and code != 0
            )
            if should_restart:
                restart_count += 1
                # The in-place kubelet restart: the kind the controller's
                # pastBackoffLimit sums (vs EXIT_CODE pod replacement,
                # counted at the controller with reason preempt/exit_code).
                status_metrics.restarts_total.labels(
                    namespace=pod.namespace, reason="backoff"
                ).inc()
                self._set_status(pod, PodPhase.RUNNING, code, restart_count)
                time.sleep(min(0.1 * restart_count, 2.0))
                # The pod may have been deleted during the backoff sleep —
                # respawning then would orphan a process forever (Always
                # policy) with no pod object tracking it.
                if entry.stopping or self._stopped:
                    return
                cur = self.cluster.try_get_pod(pod.namespace, pod.name)
                if cur is None or cur.metadata.uid != pod.metadata.uid:
                    return
                continue

            phase = PodPhase.SUCCEEDED if code == 0 else PodPhase.FAILED
            self._set_status(pod, phase, code, restart_count)
            with self._lock:
                # Only pop our own entry: an ExitCode re-creation may have
                # already registered a successor under the same (ns, name).
                cur_entry = self._procs.get((pod.namespace, pod.name))
                if cur_entry is entry:
                    self._procs.pop((pod.namespace, pod.name), None)
            return

    def _set_status(
        self,
        pod: Pod,
        phase: PodPhase,
        exit_code: int | None,
        restart_count: int,
        reason: str = "",
        endpoint: str | None = None,
    ) -> None:
        # Re-read + retry: against a real API server a concurrent write (the
        # controller patching labels, another status bump) 409s; dropping a
        # phase transition would wedge the job's state machine, so terminal
        # phases retry much harder than intermediate ones.
        terminal = phase in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        attempts = 40 if terminal else 5
        for _ in range(attempts):
            try:
                cur = self.cluster.get_pod(pod.namespace, pod.name)
            except NotFoundError:
                return  # pod deleted; nothing to report status on
            except Exception:
                time.sleep(0.05)  # transient read failure: retry like writes
                continue
            if cur.metadata.uid != pod.metadata.uid:
                return  # replaced by a newer pod with the same name
            cur.status.phase = phase
            if cur.status.start_time is None and phase != PodPhase.PENDING:
                cur.status.start_time = time.time()
            if endpoint:
                cur.metadata.annotations[ENDPOINT_ANNOTATION] = endpoint
            cname = pod.spec.containers[0].name
            cs = next(
                (c for c in cur.status.container_statuses if c.name == cname),
                None,
            )
            if cs is None:
                cs = ContainerStatus(name=cname)
                cur.status.container_statuses.append(cs)
            cs.running = phase == PodPhase.RUNNING
            cs.exit_code = exit_code
            cs.restart_count = restart_count
            cs.reason = reason
            try:
                self.cluster.update_pod_status(cur)
                return
            except Exception:
                time.sleep(0.05)  # conflict/transient: re-read and retry
        logger_for_pod(pod.namespace, pod.name).error(
            "dropping pod status write after %d attempts (phase=%s exit=%s)",
            attempts, phase, exit_code,
        )

    # ------------------------------------------------------------------ stop

    def prewarm(self, timeout: float = 30.0) -> bool:
        """Block until the prespawn fork server is ready (deploy-time cost,
        not job time); True if pods will fork pre-imported."""
        fn = getattr(self._supervisor, "prewarm", None)
        return bool(fn(timeout)) if fn else False

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            p.stopping = True
            self._terminate(p.process)
        deadline = time.time() + 5
        for p in procs:
            remaining = max(0.1, deadline - time.time())
            try:
                p.process.wait(timeout=remaining)
            except TimeoutError:
                p.process.kill()
            except ProcessLookupError:
                pass  # already reaped+released by its pod thread
        stop_fn = getattr(self._supervisor, "stop", None)
        if stop_fn:
            stop_fn()  # shut down the prespawn fork server (kills its pods)

    def port_map(self, job_name: str, namespace: str = "default") -> PortMap | None:
        with self._lock:
            return self._port_maps.get((namespace, job_name))
