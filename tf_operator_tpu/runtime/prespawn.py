"""Pre-warmed pod fork server: cut the per-pod Python/JAX import tax.

Motivation (measured on the bench host): every pod process pays ~2.7 s of
interpreter boot because the TPU environment's sitecustomize imports jax at
startup, plus ~1 s of flax/optax/model imports — serialized before the
trainer's first line runs. The reference never faces this (its data plane
boots inside user containers it doesn't time), but our north-star metric is
submit -> Succeeded wall-clock (BASELINE.md), and the import tax is the
single largest startup segment.

Fix: a long-lived fork server per runtime. It imports the heavy modules
ONCE (jax, flax, optax, the model zoo entrypoint — never initializing the
TPU backend: each forked child dials the chip itself), then serves fork
requests over a unix socket. A pod whose command is `python -m mod ...`
becomes: fork -> setsid -> redirect stdio to the pod log -> swap env ->
runpy.run_module(mod). Fork + COW pages make pod start ~milliseconds of
import work instead of ~4 s.

Safety properties:
  - The server NEVER initializes a JAX backend (preload imports only);
    children that set JAX_PLATFORMS re-point jax.config before user code.
  - Any failure (server missing, socket error, ineligible command) falls
    back to the normal supervisor spawn — prespawn is an optimization,
    never a correctness dependency. TPUJOB_PRESPAWN=0 disables it.
  - The server is single-threaded (accept loop + WNOHANG reaping), so
    fork() never races another server thread holding a lock.
  - Children are process-group leaders (setsid), signaled via killpg like
    the Popen/native supervisors; exits are normalized to 128+sig.
  - The server exits when its parent dies (ppid watchdog) and kills any
    children it still owns.

Protocol (one JSON line per connection, one JSON line back):
  {"ping": true}                                    -> {"ok": true, "preloaded": [...]}
  {"spawn": {"module": m, "argv": [...], "env": {...},
             "cwd": c|null, "logfile": p|null}}     -> {"pid": N} | {"error": s}
  {"poll": pid}                                     -> {"exit": code|null}
  {"signal": pid, "sig": n}                         -> {"ok": true}
  {"shutdown": true}                                -> {"ok": true}  (then exits)
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

DEFAULT_PRELOAD = (
    "jax,flax,optax,chex,numpy,"
    "tf_operator_tpu.models.train,tf_operator_tpu.parallel.train_step,"
    "tf_operator_tpu.testing.workload"
)


def _norm_status(status: int) -> int:
    """waitpid status -> exit code, signal deaths as 128+sig (supervisor
    contract, native/tpujob_native.cc twin)."""
    if os.WIFSIGNALED(status):
        return 128 + os.WTERMSIG(status)
    return os.WEXITSTATUS(status)


# --------------------------------------------------------------------- server


class _Server:
    def __init__(self, sock_path: str, preload: str):
        self.sock_path = sock_path
        self.preload = [m for m in preload.split(",") if m]
        self.exits: dict[int, int] = {}
        self.live: set[int] = set()
        self.parent = os.getppid()

    def _preload(self) -> list[str]:
        done = []
        for mod in self.preload:
            try:
                __import__(mod)
                done.append(mod)
            except Exception as e:  # preload is best-effort by design
                print(f"prespawn: preload {mod} failed: {e}", file=sys.stderr)
        return done

    def _reap(self) -> None:
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            self.exits[pid] = _norm_status(status)
            self.live.discard(pid)

    def _fork(self, req: dict) -> dict:
        module, argv = req["module"], req.get("argv", [])
        env, cwd = req.get("env") or {}, req.get("cwd")
        logfile = req.get("logfile")
        pid = os.fork()
        if pid:
            # A recycled pid must not inherit a prior pod's exit record.
            self.exits.pop(pid, None)
            self.live.add(pid)
            return {"pid": pid}
        # ---- child ----
        try:
            os.setsid()
            for s in (signal.SIGTERM, signal.SIGINT, signal.SIGCHLD):
                signal.signal(s, signal.SIG_DFL)
            fd = (os.open(logfile, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                          0o644) if logfile
                  else os.open(os.devnull, os.O_WRONLY))
            devnull_in = os.open(os.devnull, os.O_RDONLY)
            os.dup2(devnull_in, 0)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            try:
                # Line-buffer the redirected stdio: a real pod spawn writes
                # to its logfile promptly, and a block-buffered tail would be
                # lost on SIGKILL (the dashboard log endpoint reads this file
                # live).
                sys.stdout.reconfigure(line_buffering=True)
                sys.stderr.reconfigure(line_buffering=True)
            except (AttributeError, OSError, ValueError):
                pass
            if cwd:
                os.chdir(cwd)
            os.environ.clear()
            os.environ.update(env)
            # jax.config captured JAX_PLATFORMS at server import; re-point it
            # for pods that choose a different backend (e.g. CPU test pods).
            if "jax" in sys.modules and env.get("JAX_PLATFORMS"):
                try:
                    import jax

                    if jax.config.jax_platforms != env["JAX_PLATFORMS"]:
                        jax.config.update("jax_platforms", env["JAX_PLATFORMS"])
                except Exception:
                    pass
            # PYTHONPATH is normally consumed at interpreter start; emulate
            # for the pod's env so non-preloaded modules resolve.
            for p in reversed((env.get("PYTHONPATH") or "").split(os.pathsep)):
                if p and p not in sys.path:
                    sys.path.insert(0, p)
            import runpy

            sys.argv = [module] + list(argv)
            code = 0
            try:
                runpy.run_module(module, run_name="__main__", alter_sys=True)
            except SystemExit as e:
                code = e.code if isinstance(e.code, int) else (0 if e.code is None else 1)
            except BaseException:
                import traceback

                traceback.print_exc()
                code = 1
        except BaseException:
            code = 1
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(code)

    def _handle(self, req: dict) -> dict | None:
        if "ping" in req:
            return {"ok": True, "preloaded": self.preloaded}
        if "spawn" in req:
            try:
                return self._fork(req["spawn"])
            except OSError as e:
                return {"error": f"fork: {e}"}
        if "poll" in req:
            pid = req["poll"]
            self._reap()
            if pid in self.exits:
                # One handle per pid, and it caches the code on first read:
                # dropping the entry bounds `exits` and removes the pid-reuse
                # window entirely.
                return {"exit": self.exits.pop(pid)}
            return {"exit": None}
        if "signal" in req:
            try:
                os.killpg(req["signal"], req["sig"])
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(req["signal"], req["sig"])
                except ProcessLookupError:
                    pass
            return {"ok": True}
        if "shutdown" in req:
            return {"ok": True, "_shutdown": True}
        return {"error": "bad request"}

    def run(self) -> int:
        self.preloaded = self._preload()
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        srv.bind(self.sock_path)
        srv.listen(16)
        srv.settimeout(0.2)
        print(f"prespawn: ready ({len(self.preloaded)} modules) on "
              f"{self.sock_path}", file=sys.stderr, flush=True)
        try:
            while True:
                self._reap()
                if os.getppid() != self.parent:  # runtime died; don't linger
                    break
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                # accept() sockets are blocking regardless of the listener's
                # timeout; a silent client must not wedge the accept loop.
                conn.settimeout(5.0)
                with conn:
                    try:
                        data = conn.makefile("rb").readline()
                        resp = self._handle(json.loads(data))
                        conn.sendall((json.dumps(resp) + "\n").encode())
                    except Exception as e:
                        try:
                            conn.sendall(
                                (json.dumps({"error": str(e)}) + "\n").encode()
                            )
                        except OSError:
                            pass
                        continue
                    if resp and resp.get("_shutdown"):
                        break
        finally:
            for pid in list(self.live):
                try:
                    os.killpg(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            srv.close()
            try:
                os.unlink(self.sock_path)
            except FileNotFoundError:
                pass
        return 0


# --------------------------------------------------------------------- client


class PrespawnProcess:
    """Supervisor-process handle backed by the fork server (same interface
    as _PopenProcess / NativeProcess)."""

    def __init__(self, client: "PrespawnClient", pid: int):
        self._client = client
        self.pid = pid
        self._exit: int | None = None
        self._poll_lock = threading.Lock()

    def poll(self) -> int | None:
        # Serialized: the server's exit record is a destructive read (popped
        # on first report), and several threads poll one handle (the owning
        # pod thread's wait() plus drain/purge scans) — a second in-flight
        # poll must not clobber the cached code with None.
        with self._poll_lock:
            return self._poll_locked()

    def _poll_locked(self) -> int | None:
        if self._exit is not None:
            return self._exit
        resp = self._client.request({"poll": self.pid})
        if resp is None:
            # Transient socket failure is NOT process death: only declare the
            # pod dead once the server process itself is gone (its children
            # die with it: the server SIGKILLs its process groups on exit,
            # and an abrupt server death reparents+orphans them, so the
            # conservative report is a signal death).
            if self._client.server_dead():
                self._exit = 128 + signal.SIGKILL
                return self._exit
            return None
        self._exit = resp.get("exit")
        return self._exit

    def wait(self, timeout: float | None = None) -> int:
        deadline = time.time() + timeout if timeout is not None else None
        delay = 0.02  # exponential backoff: pods live seconds-to-hours, and
        while True:   # each poll is a full round trip through one accept loop
            code = self.poll()
            if code is not None:
                return code
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"pid {self.pid} still running")
            time.sleep(delay)
            delay = min(delay * 1.5, 0.5)

    def _signal(self, sig: int) -> None:
        self._client.request({"signal": self.pid, "sig": sig})

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def release(self) -> None:
        pass


class PrespawnClient:
    def __init__(self, sock_path: str):
        self.sock_path = sock_path
        self._proc: subprocess.Popen | None = None
        self._lock = threading.Lock()
        self._ready = False

    def start(self, preload: str | None = None) -> None:
        """Launch the server (non-blocking; readiness via ready())."""
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._proc = subprocess.Popen(
                [sys.executable, "-m", "tf_operator_tpu.runtime.prespawn",
                 "--socket", self.sock_path,
                 "--preload", preload or os.environ.get(
                     "TPUJOB_PRESPAWN_PRELOAD", DEFAULT_PRELOAD)],
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
                start_new_session=True,
            )

    def request(self, req: dict, timeout: float = 10.0) -> dict | None:
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.settimeout(timeout)
                s.connect(self.sock_path)
                s.sendall((json.dumps(req) + "\n").encode())
                line = s.makefile("rb").readline()
            return json.loads(line) if line else None
        except (OSError, ValueError):
            return None

    def server_dead(self) -> bool:
        """True only when the server process is known to have exited."""
        with self._lock:
            return self._proc is not None and self._proc.poll() is not None

    def ready(self) -> bool:
        if self._ready:
            return True
        resp = self.request({"ping": True}, timeout=0.5)
        self._ready = bool(resp and resp.get("ok"))
        return self._ready

    def prewarm(self, timeout: float = 30.0) -> bool:
        """Block until the server is ready (operator startup, not job time)."""
        self.start()
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.ready():
                return True
            if self._proc is not None and self._proc.poll() is not None:
                return False  # server died during warmup
            time.sleep(0.1)
        return False

    def stop(self) -> None:
        if self._proc is None:
            return
        acked = self.request({"shutdown": True}, timeout=2.0)
        if acked is None and not self._ready:
            # Never served a request — still BOOTING (importing jax, socket
            # not yet listening; a short-lived session hits this every
            # time). No forked children can exist before the first serve,
            # so SIGKILL now instead of burning a 3 s grace wait. A server
            # that HAS served (self._ready) keeps the grace period even on
            # a timed-out reply: it may be busy with an in-flight request,
            # and killing it would skip its finally-block child reaping.
            self._proc.kill()
        try:
            self._proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()


# ----------------------------------------------------------------- supervisor


def parse_module_cmd(cmd: list[str]) -> tuple[str, list[str]] | None:
    """(module, argv) when cmd is `python [-u|-B] -m module args...`.

    Only THIS interpreter qualifies: the fork server can't run a pod under a
    different Python than its own, so a versioned request like `python3.11`
    must fall through to a real spawn rather than silently running here.
    """
    if len(cmd) < 3:
        return None
    if cmd[0] != sys.executable:
        # Bare names resolve to this interpreter on PATH-less pod specs;
        # an explicit path to a DIFFERENT python (another venv) must fall
        # through to a real spawn, not run under our site-packages.
        if os.path.dirname(cmd[0]):
            if os.path.realpath(cmd[0]) != os.path.realpath(sys.executable):
                return None
        elif cmd[0] not in ("python", "python3",
                            os.path.basename(sys.executable)):
            return None
    i = 1
    while i < len(cmd) and cmd[i] in ("-u", "-B"):
        i += 1
    if i + 1 >= len(cmd) or cmd[i] != "-m":
        return None
    return cmd[i + 1], list(cmd[i + 2:])


class PrespawnSupervisor:
    """Routes `python -m` pod commands through the fork server; everything
    else (and every failure) goes to the wrapped base supervisor."""

    def __init__(self, base, sock_path: str):
        self.base = base
        self.client = PrespawnClient(sock_path)
        self._started = False

    def _ensure_started(self) -> None:
        # Lazy: runtimes that never spawn a `python -m` pod (plenty of unit
        # tests do not) must not pay a jax-importing background process.
        if not self._started:
            self._started = True
            self.client.start()

    def prewarm(self, timeout: float = 30.0) -> bool:
        self._ensure_started()
        return self.client.prewarm(timeout)

    def spawn(self, cmd, env=None, cwd=None, logfile=None):
        parsed = parse_module_cmd(list(cmd))
        if parsed is not None:
            self._ensure_started()
        if parsed is not None and self.client.ready():
            module, argv = parsed
            # env=None means inherit, like Popen: snapshot the runtime's env
            # rather than handing the child an empty environment.
            resp = self.client.request({"spawn": {
                "module": module, "argv": argv,
                "env": dict(os.environ) if env is None else dict(env),
                "cwd": cwd, "logfile": logfile,
            }})
            if resp and "pid" in resp:
                return PrespawnProcess(self.client, resp["pid"])
        return self.base.spawn(cmd, env=env, cwd=cwd, logfile=logfile)

    def stop(self) -> None:
        self.client.stop()


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="prespawn")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--preload", default=DEFAULT_PRELOAD)
    args = ap.parse_args(argv)
    return _Server(args.socket, args.preload).run()


if __name__ == "__main__":
    sys.exit(main())
