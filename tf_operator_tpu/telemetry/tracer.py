"""In-process span tracer with Chrome trace-event export.

The observability substrate every per-PR ad-hoc timer dict grew toward:
one low-overhead tracer that the trainer hot loop, the staging/prefetch
transfer threads, and checkpoint save/restore all record into, exported
as Chrome trace-event JSON (the format Perfetto and chrome://tracing
load natively — and the same family jax.profiler emits, so a tpujob
trace and an XProf device trace can sit side by side).

Design constraints, in priority order:

  1. **Near-zero cost when disabled.** `span()` on a disabled tracer
     returns a shared no-op context manager after ONE attribute read —
     no allocation beyond the kwargs dict, no clock read, no lock. The
     hot paths (per-step loop, per-batch transfer thread) call it
     unconditionally; tests/test_telemetry.py pins the disabled cost.
  2. **Bounded memory.** Events land in a ring buffer
     (collections.deque(maxlen=capacity)); a long run overwrites its
     oldest events instead of growing. `dropped_events` reports how many
     were evicted so a truncated export is visible, not silent.
  3. **Thread-safe.** Spans may begin and end on different threads
     (`begin()`/`end()` — the staging ring stages on a producer thread
     that the consumer accounts for); `span()` context managers record
     on whatever thread runs them. Recording takes a short lock (append
     + drop counter move together, so dropped_events stays exact under
     concurrent recorders); export snapshots under the same lock. The
     DISABLED path takes no lock at all.
  4. **Monotonic clocks.** All timestamps are time.perf_counter_ns()
     deltas from the tracer's epoch — wall-clock steps (NTP, suspend)
     cannot produce negative durations or reordered events.

Chrome trace-event mapping: completed spans are "X" (complete) events
with microsecond `ts`/`dur`; `instant()` is an "i" event; process/thread
names are "M" metadata events. See the trace-event format spec
(docs/perf.md round-8 section explains how to read one).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any

__all__ = [
    "Tracer", "get_tracer", "configure", "span", "begin", "end", "instant",
]


class _NullSpan:
    """Shared no-op context manager: the entire disabled-tracer fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """An open span; records one "X" event when closed. Carries the thread
    id it was OPENED on, so begin()/end() pairs that cross threads still
    render on the opening thread's track."""

    __slots__ = ("_tracer", "name", "attrs", "_t0", "_tid")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._tid = threading.get_ident()
        # Name the track NOW, on the opening thread: a cross-thread span
        # recorded at end() would otherwise stamp the CLOSING thread's
        # name onto the opening thread's track.
        tracer._note_thread(self._tid)
        self._t0 = time.perf_counter_ns()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._tracer._record(self)
        return False


class Tracer:
    def __init__(self, capacity: int = 65536, enabled: bool = False):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.enabled = enabled
        self.capacity = capacity
        # Ring buffer of (name, t0_ns, dur_ns, tid, attrs) tuples; "i"
        # instants carry dur_ns = -1. Appends happen under _lock together
        # with the drop counter (see _record) — enabled-path cost only.
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._epoch_ns = time.perf_counter_ns()
        self._appended = 0
        self._lock = threading.Lock()
        # Thread names snapshotted at record time (threading.enumerate at
        # export would miss already-finished transfer threads).
        self._thread_names: dict[int, str] = {}

    # ------------------------------------------------------------ recording

    def span(self, name: str, /, **attrs: Any) -> "_Span | _NullSpan":
        """Context manager timing one block on the current thread."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def begin(self, name: str, /, **attrs: Any) -> "_Span | None":
        """Open a span explicitly (cross-thread: close with end())."""
        if not self.enabled:
            return None
        return _Span(self, name, attrs)

    def end(self, handle: "_Span | None", **attrs: Any) -> None:
        """Close a begin() handle (None-safe: begin() on a disabled tracer
        returns None and end() ignores it, so callers never branch)."""
        if handle is None:
            return
        if attrs:
            handle.attrs.update(attrs)
        self._record(handle)

    def instant(self, name: str, /, **attrs: Any) -> None:
        """Mark a point in time (Chrome "i" event)."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        self._note_thread(tid)
        # Lock the append + count together: the step loop and the
        # staging/prefetch threads record concurrently, and an unguarded
        # `_appended += 1` loses increments — dropped_events would then
        # under-report, letting a truncated export claim completeness.
        # Enabled-path-only cost; the disabled path never gets here.
        with self._lock:
            self._events.append(
                (name, time.perf_counter_ns(), -1, tid, attrs or None))
            self._appended += 1

    def _record(self, sp: _Span) -> None:
        dur = time.perf_counter_ns() - sp._t0
        with self._lock:
            self._events.append(
                (sp.name, sp._t0, dur, sp._tid, sp.attrs or None))
            self._appended += 1

    def _note_thread(self, tid: int) -> None:
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name

    # ------------------------------------------------------------ inspection

    def __len__(self) -> int:
        return len(self._events)

    @property
    def dropped_events(self) -> int:
        """Events evicted by the ring (0 = the export is complete)."""
        return max(0, self._appended - len(self._events))

    def clear(self) -> None:
        """Drop recorded events and restart the timestamp epoch (a reused
        tracer's next trace starts at ts=0, like a fresh process)."""
        with self._lock:
            self._events.clear()
            self._appended = 0
            self._epoch_ns = time.perf_counter_ns()
            # Thread names too: Python reuses thread idents, and a stale
            # name from a previous trace window would label a NEW thread's
            # track with a dead thread's name.
            self._thread_names.clear()

    # --------------------------------------------------------------- export

    def chrome_trace(self) -> dict:
        """The trace as a Chrome trace-event JSON object (dict form)."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
        pid = os.getpid()
        # Stable small tids: Chrome renders one track per (pid, tid), and
        # raw Python idents are unreadable 15-digit numbers.
        tid_map = {raw: i for i, raw in enumerate(
            sorted({e[3] for e in events} | set(names)))}
        out: list[dict] = [{
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "tpujob-trainer"},
        }]
        for raw, small in sorted(tid_map.items(), key=lambda kv: kv[1]):
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": small,
                "args": {"name": names.get(raw, f"thread-{small}")},
            })
        for name, t0, dur, tid, attrs in events:
            ev: dict = {
                "name": name,
                "cat": "tpujob",
                "pid": pid,
                "tid": tid_map[tid],
                "ts": (t0 - self._epoch_ns) / 1000.0,  # microseconds
            }
            if dur < 0:
                ev["ph"] = "i"
                ev["s"] = "t"  # instant scoped to its thread
            else:
                ev["ph"] = "X"
                ev["dur"] = dur / 1000.0
            if attrs:
                ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
            out.append(ev)
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": self.dropped_events},
        }

    def export(self, path: str) -> int:
        """Write the Chrome trace JSON to `path` (dirs created); returns
        the number of non-metadata events written."""
        trace = self.chrome_trace()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return sum(1 for e in trace["traceEvents"] if e["ph"] != "M")


def _jsonable(v: Any):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


# Module-level default tracer: the zero-wiring path every subsystem
# (trainer loop, staging/prefetch threads, checkpoint IO) records into.
_DEFAULT = Tracer()


def get_tracer() -> Tracer:
    return _DEFAULT


def configure(enabled: bool | None = None, capacity: int | None = None) -> Tracer:
    """Configure the default tracer (the trainer's --trace flag lands
    here). Changing capacity re-allocates the ring, dropping recorded
    events — configure before tracing starts."""
    global _DEFAULT
    if capacity is not None and capacity != _DEFAULT.capacity:
        _DEFAULT = Tracer(capacity=capacity, enabled=_DEFAULT.enabled)
    if enabled is not None:
        _DEFAULT.enabled = enabled
    return _DEFAULT


def span(name: str, /, **attrs: Any):
    """`with telemetry.span("staging.h2d", bytes=n):` on the default
    tracer — one attribute read when disabled."""
    t = _DEFAULT
    if not t.enabled:
        return _NULL_SPAN
    return _Span(t, name, attrs)


def begin(name: str, /, **attrs: Any):
    return _DEFAULT.begin(name, **attrs)


def end(handle, **attrs: Any) -> None:
    _DEFAULT.end(handle, **attrs)


def instant(name: str, /, **attrs: Any) -> None:
    _DEFAULT.instant(name, **attrs)
