"""Unified telemetry: span tracer, per-step phase accounting, collector.

Three layers, one substrate (ROADMAP round 8):

  * tracer.py — low-overhead in-process span tracer (`span()` context
    manager, cross-thread `begin`/`end`, bounded ring buffer, near-zero
    cost disabled) exporting Chrome trace-event JSON for Perfetto /
    chrome://tracing. The trainer's `--trace` flag drives it.
  * phases.py — per-step phase accounting on top of the tracer:
    data_wait / h2d_transfer / dispatch / device_blocked / checkpoint /
    eval / other, telescoping exactly to step wall-clock, with weighted
    per-step percentiles for the done event's `step_time_s`.
  * collector.py — control-plane side: reads the pods' trainer event
    files back into per-job API `telemetry` blocks and labeled
    `tpujob_trainer_*` gauges on /metrics (imported by cli/server.py;
    not re-exported here to keep data-plane imports stdlib-only).

Import cost matters: models/train.py imports this before jax, and the
staging/prefetch transfer threads call `span()` per batch — everything
here is stdlib.
"""

from tf_operator_tpu.telemetry.phases import (  # noqa: F401
    PHASES,
    NullStepAccounting,
    StepAccounting,
    make_step_accounting,
    weighted_percentile,
)
from tf_operator_tpu.telemetry.tracer import (  # noqa: F401
    Tracer,
    begin,
    configure,
    end,
    get_tracer,
    instant,
    span,
)
