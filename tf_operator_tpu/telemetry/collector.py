"""Trainer-telemetry collector: metrics files -> API + /metrics.

The trainer emits JSON event lines (start/first_step/progress/checkpoint/
done — models/train.py) to TPUJOB_METRICS_FILE. The local runtime points
each pod at `<log_dir>/{ns}_{pod}.metrics.jsonl` (runtime/local.py), and
this collector reads those files back on demand to surface the data
plane's telemetry through the control plane:

  * `GET /api/trainjobs/{ns}/{name}` carries a per-job `telemetry` block
    (per-replica: latest step/loss, startup_s, steady steps/sec, the
    round-8 step_time_s percentiles and phase_breakdown, staging/
    prefetch accounting) — cli/server.py calls `job_telemetry`.
  * `GET /metrics` exposes labeled `tpujob_trainer_*` gauges
    ({namespace=...,job=...} child series, status/metrics.py labels) —
    cli/server.py calls `refresh_gauges` per scrape (pull model: files
    are read when someone looks, never on a hot path).

Files are re-read per request rather than tailed: trainer event files
are a few KB (one line per log_every steps), and a stateless read makes
the collector correct across pod restarts and operator failover.
"""

from __future__ import annotations

import json
import os
import re
import time

from tf_operator_tpu.status import metrics as metrics_mod
from tf_operator_tpu.telemetry import journal as journal_mod
from tf_operator_tpu.utils.preemption import read_heartbeat

__all__ = ["TRAINER_GAUGES", "TelemetryCollector", "summarize_events"]

# Every trainer gauge this collector can expose, name -> help text.
# tools/check_metrics_doc.py audits docs/monitoring.md against this dict,
# so a gauge added here without a doc row fails CI.
TRAINER_GAUGES = {
    "tpujob_trainer_steps_per_sec":
        "Steady-state training steps/sec from the trainer's done event",
    "tpujob_trainer_examples_per_sec":
        "Steady-state examples/sec from the trainer's done event",
    "tpujob_trainer_last_step":
        "Latest step the trainer reported (progress/done events)",
    "tpujob_trainer_loss":
        "Latest training loss the trainer reported",
    "tpujob_trainer_startup_s":
        "Pod start -> first optimizer step, seconds (first_step event)",
    "tpujob_trainer_step_time_p50_s":
        "Median per-step wall-clock from the done event's step_time_s",
    "tpujob_trainer_step_time_p99_s":
        "p99 per-step wall-clock from the done event's step_time_s",
    "tpujob_heartbeat_age_seconds":
        "Seconds since the job's freshest trainer progress heartbeat "
        "(TPUJOB_HEARTBEAT_FILE; the hang-watchdog's staleness signal)",
    "tpujob_trainer_transfer_mb_per_s":
        "Staged-ingest host->device transfer rate (bytes over wire-busy "
        "union across lanes) from the done event's staging accounting",
    "tpujob_trainer_ckpt_hidden_fraction":
        "Share of checkpoint write time hidden behind training by the "
        "async writer (done event's checkpoint block; 0.0 = sync saves, "
        "1.0 = the step loop paid only the snapshot leg)",
    "tpujob_trainer_dcn_hidden_fraction":
        "Multi-slice jobs: share of cross-slice (DCN) gradient-exchange "
        "time hidden behind backward compute by the bucketed reduction "
        "(done event's dcn block; 0.0 = fully visible sync, 1.0 = the "
        "step loop never waited on the wire)",
}

# Pod names are {job}-{type}-{index} (utils/naming.py); anchoring on the
# replica-type vocabulary keeps job "a" from claiming job "a-worker"'s
# files. "server" is the InferenceService replica type (serve/).
_REPLICA_RE = r"(chief|master|worker|ps|evaluator|server)-\d+"


def _read_events(path: str) -> list[dict]:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        pass  # torn write mid-append: skip the line
    except OSError:
        pass
    return out


def summarize_events(events: list[dict]) -> dict | None:
    """One replica's event stream -> the telemetry block the API serves.
    Restart-safe: a restarted pod appends a second start event to the
    same file; the summary reflects the LATEST attempt's events while
    counting attempts."""
    if not events:
        return None
    attempts = sum(1 for e in events if e.get("event") == "start") or 1
    last_start = max((i for i, e in enumerate(events)
                      if e.get("event") == "start"), default=0)
    cur = events[last_start:]
    by = {}
    for e in cur:
        by[e.get("event")] = e  # last occurrence wins
    out: dict = {
        "last_event": cur[-1].get("event"),
        "attempts": attempts,
        "phase": "done" if "done" in by else (
            "training" if "first_step" in by else "starting"),
    }
    first = by.get("first_step", {})
    if first.get("startup_s") is not None:
        out["startup_s"] = first["startup_s"]
    prog = by.get("progress") or {}
    done = by.get("done") or {}
    step = done.get("steps", prog.get("step"))
    if step is not None:
        out["step"] = step
    loss = done.get("final_loss", prog.get("loss", first.get("loss")))
    if loss is not None:
        out["loss"] = loss
    for k in ("steady_steps_per_sec", "examples_per_sec", "total_s",
              "step_time_s", "phase_breakdown", "staging", "prefetch",
              "checkpoint", "dcn"):
        if done.get(k) is not None:
            out[k] = done[k]
    if by.get("trace_done"):
        out["trace_path"] = by["trace_done"].get("path")
    return out


class TelemetryCollector:
    def __init__(self, log_dir: str, registry: metrics_mod.Registry | None = None):
        self.log_dir = log_dir
        self.registry = registry or metrics_mod.DEFAULT
        # labels_only: these families exist purely as per-job child
        # series — a bare 0-valued sample before the first job reported
        # would plot as a phantom job on every dashboard.
        self._gauges = {
            name: self.registry.gauge(name, help_text, labels_only=True)
            for name, help_text in TRAINER_GAUGES.items()
        }

    # ------------------------------------------------------------- reading

    def _job_files(self, namespace: str, job: str,
                   suffix: str = r"\.metrics\.jsonl") -> dict[str, str]:
        """pod name -> per-pod file path for every replica of the job that
        ever wrote one (globbing the log_dir covers pods that have already
        been deleted — their last telemetry outlives them). `suffix` picks
        the file family: metrics events by default, heartbeats via
        _job_heartbeat_files."""
        # Filename layout mirrors the runtime's log files ({ns}_{pod}.log).
        pat = re.compile(
            rf"^{re.escape(namespace)}_({re.escape(job)}-{_REPLICA_RE})"
            rf"{suffix}$"
        )
        out: dict[str, str] = {}
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return out
        for fn in names:
            m = pat.match(fn)
            if m:
                out[m.group(1)] = os.path.join(self.log_dir, fn)
        return out

    def _job_heartbeat_files(self, namespace: str, job: str) -> dict[str, str]:
        """pod name -> heartbeat-file path (runtime-injected
        TPUJOB_HEARTBEAT_FILE, same naming scheme as the metrics files).
        Evaluator replicas are EXCLUDED, mirroring the controller's gang
        exemption: they sit outside the collective and their trainer
        process only force-writes heartbeats at startup milestones, never
        in the eval polling loop — aggregating that one-shot signal would
        arm the hang watchdog for a gang whose workers never heartbeat
        and then read permanently stale, rolling a healthy job to
        BackoffLimitExceeded."""
        return {
            pod: path
            for pod, path in self._job_files(
                namespace, job, suffix=r"\.heartbeat\.json").items()
            if not pod.startswith(f"{job}-evaluator-")
        }

    def job_heartbeat(self, namespace: str, job: str) -> dict | None:
        """The job's aggregated progress heartbeat, or None when no replica
        has written one yet. `step` is the high-water step across replicas,
        `t` the FRESHEST write — a gang is only 'hung' once even its most
        recent member has gone quiet (when one host dies the survivors
        wedge in the collective, so all heartbeats go stale together).
        This is the controller's heartbeat_source interface."""
        per_pod: dict[str, dict] = {}
        for pod, path in sorted(self._job_heartbeat_files(namespace, job).items()):
            hb = read_heartbeat(path)
            if hb is not None:
                per_pod[pod] = hb
        if not per_pod:
            return None
        step = max((hb.get("step") or 0) for hb in per_pod.values())
        t = max((hb.get("t") or 0.0) for hb in per_pod.values())
        return {
            "step": int(step),
            "t": float(t),
            "age_seconds": max(0.0, time.time() - float(t)),
            "replicas": per_pod,
        }

    def service_load(self, namespace: str, service: str) -> dict[str, dict]:
        """pod name -> latest serve-stats snapshot ({inflight,
        requests_total, served_total, latency_p50_ms/_p99_ms, t}) for an
        InferenceService's server replicas. The server writes the file
        atomically (tmp+replace), so a torn read means 'no stats yet'.
        The serve controller sums `inflight` over LIVE replicas — this
        is the autoscaler's load signal."""
        out: dict[str, dict] = {}
        for pod, path in sorted(self._job_files(
                namespace, service, suffix=r"\.serve\.json").items()):
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(snap, dict):
                out[pod] = snap
        return out

    def job_telemetry(self, namespace: str, job: str) -> dict | None:
        """The per-job `telemetry` block for GET /api/trainjobs/{ns}/{name}:
        {"replicas": {pod: summary}} or None when no replica reported."""
        replicas = {}
        for pod, path in sorted(self._job_files(namespace, job).items()):
            summary = summarize_events(_read_events(path))
            if summary:
                replicas[pod] = summary
        hb = self.job_heartbeat(namespace, job)
        if not replicas and hb is None:
            return None
        out: dict = {"replicas": replicas}
        if hb is not None:
            out["heartbeat"] = {
                "step": hb["step"],
                "t": hb["t"],
                "age_seconds": round(hb["age_seconds"], 3),
            }
        return out

    # -------------------------------------------------------------- gauges

    @staticmethod
    def _primary(replicas: dict[str, dict]) -> dict | None:
        """The replica whose numbers represent the job on /metrics: the
        writer role (chief/master, else worker-0 — the same replica the
        checkpoint contract elects), falling back to the furthest-along
        replica."""
        for pod, s in replicas.items():
            if re.search(r"-(chief|master)-0$", pod):
                return s
        for pod, s in replicas.items():
            if pod.endswith("-worker-0"):
                return s
        return max(replicas.values(),
                   key=lambda s: s.get("step", -1), default=None)

    def refresh_gauges(self, cluster) -> None:
        """Pull-model update: called per /metrics scrape. Jobs come from
        the cluster substrate and child series of jobs no longer in it
        are REMOVED, so label cardinality is bounded by live jobs — a
        weeks-long operator with job churn must not accumulate a frozen
        gauge per deleted job."""
        live = {(job.namespace, job.name) for job in cluster.list_jobs()}
        for gauge in self._gauges.values():
            for ls in gauge.labelsets():
                if (ls.get("namespace"), ls.get("job")) not in live:
                    gauge.remove(**ls)
        for job in cluster.list_jobs():
            tel = self.job_telemetry(job.namespace, job.name)
            if not tel:
                continue
            labels = {"namespace": job.namespace, "job": job.name}
            hb = tel.get("heartbeat")
            if hb is not None:
                # Recomputed per scrape, not cached: age grows between
                # trainer writes, and a frozen age is exactly the signal
                # a hang dashboard alerts on.
                self._gauges["tpujob_heartbeat_age_seconds"].labels(
                    **labels).set(float(hb["age_seconds"]))
            primary = self._primary(tel["replicas"])
            if not primary:
                continue
            step_time = primary.get("step_time_s") or {}
            staging = primary.get("staging") or {}
            ckpt = primary.get("checkpoint") or {}
            dcn = primary.get("dcn") or {}
            for gauge_name, value in (
                ("tpujob_trainer_steps_per_sec",
                 primary.get("steady_steps_per_sec")),
                ("tpujob_trainer_examples_per_sec",
                 primary.get("examples_per_sec")),
                ("tpujob_trainer_last_step", primary.get("step")),
                ("tpujob_trainer_loss", primary.get("loss")),
                ("tpujob_trainer_startup_s", primary.get("startup_s")),
                ("tpujob_trainer_step_time_p50_s", step_time.get("p50")),
                ("tpujob_trainer_step_time_p99_s", step_time.get("p99")),
                ("tpujob_trainer_transfer_mb_per_s",
                 staging.get("transfer_mb_per_s")),
                ("tpujob_trainer_ckpt_hidden_fraction",
                 ckpt.get("hidden_fraction")),
                ("tpujob_trainer_dcn_hidden_fraction",
                 dcn.get("hidden_fraction")),
            ):
                if value is not None:
                    self._gauges[gauge_name].labels(**labels).set(float(value))
            self._observe_first_step(job, primary)

    def _observe_first_step(self, job, primary: dict) -> None:
        """Once per job: the trainer reported its startup time (imports,
        compile, checkpoint restore) — record the `first_step` journal
        event (timeline's startup->training boundary) and sample the
        startup phase histogram. The journal ring itself is the
        once-guard, so the sample survives collector restarts no worse
        than the ring does."""
        startup = primary.get("startup_s")
        if startup is None:
            return
        jrnl = journal_mod.get_journal()
        if not jrnl.enabled:
            return
        key = f"{job.namespace}/{job.name}"
        if jrnl.last_ts(key, "first_step") is not None:
            return
        jrnl.record(key, "first_step", startup_s=round(float(startup), 3))
        metrics_mod.job_phase_seconds.labels(phase="startup").observe(
            float(startup))
