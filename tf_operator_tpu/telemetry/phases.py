"""Per-step phase accounting: where every second of a training step went.

The trainer's headline has been a single steady-state mean
(steady_steps_per_sec); a p99 stall — a checkpoint save, a transfer
hiccup, one slow host batch — is invisible in a mean. This layer
decomposes every step into named phases and keeps the per-step
wall-clock distribution, under the same telescoping discipline as the
staging ring's accounting (data/staging.py: wall == wait + busy by
construction):

    step wall-clock == sum(phases) + other     (exactly, by construction)

Phase taxonomy (PHASES):

    data_wait      blocked pulling the next batch from the input
                   pipeline (prefetch/staging ring). The pipeline's own
                   telemetry says how much of what hid under compute was
                   host production vs transfer.
    h2d_transfer   synchronous host->device transfer performed by the
                   step loop itself. Under the async ingest modes the
                   transfer rides a background thread (visible as tracer
                   spans + staging stats) and this phase is ~0.
    dispatch       handing the step to the runtime (async: the call
                   returns a future; on-device execution overlaps the
                   rest of the loop body).
    device_blocked time blocked on device results (loss fetches — the
                   window-closing host transfers).
    checkpoint     SYNCHRONOUS checkpoint saves made from the step loop
                   (--checkpoint-mode sync, and the preemption fast
                   path): snapshot + serialize + manifests, all blocking.
    ckpt_snapshot  the BLOCKING leg of an async save (--checkpoint-mode
                   async, the default): device->host snapshot of the
                   train state plus any backpressure wait for the
                   previous save's write leg to drain. The write leg
                   itself (ckpt_write) rides the dedicated writer thread
                   — it appears as tracer spans and in the done event's
                   `checkpoint` block (write_s / hidden_fraction /
                   drains), never as a step phase, because it does not
                   spend step wall-clock; the telescoping identity above
                   is preserved exactly.
    dcn_sync       the VISIBLE share of the cross-slice (DCN) gradient
                   exchange (multi-slice jobs, parallel/multislice.py):
                   time the step loop blocked in collect() waiting for
                   bucket transfers that did not hide under backward
                   compute. The exchange's own clock (dcn_busy_s in the
                   done event's `dcn` block) is the TOTAL; their ratio is
                   the measured hidden_fraction.
    eval           inline evaluation from the step loop (the separate
                   Evaluator replica accounts its own process).
    other          the telescoping residual: loop body time attributed
                   to no phase (event emission, bookkeeping).

Steps are recorded via context managers; a chunked on-device loop (one
dispatch per N steps) records one sample with n_steps=N and the
percentile math weights it as N per-step samples of wall/N — the
distribution stays per-STEP whatever the dispatch granularity.

TPUJOB_TELEMETRY=off returns a no-op accountant with the same API (the
baseline for tests/test_telemetry.py's overhead guard).
"""

from __future__ import annotations

import math
import os
import time

from tf_operator_tpu.telemetry import tracer as _tracer_mod

__all__ = [
    "PHASES", "StepAccounting", "NullStepAccounting",
    "make_step_accounting", "weighted_percentile",
]

PHASES = ("data_wait", "h2d_transfer", "dispatch", "device_blocked",
          "checkpoint", "ckpt_snapshot", "dcn_sync", "eval", "other")

QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def phase(self, name: str, **attrs):
        return self


_NULL_CTX = _NullCtx()


class _Step:
    """One step (or chunk of n_steps) being accounted. Not reentrant; one
    step at a time per accountant (the train loop is sequential)."""

    __slots__ = ("_acct", "_index", "_n", "_t0", "_attributed", "_span")

    def __init__(self, acct: "StepAccounting", index: int, n_steps: int):
        self._acct = acct
        self._index = index
        self._n = n_steps
        self._attributed = 0.0
        self._span = None

    def __enter__(self):
        self._span = self._acct._tracer.begin(
            "step", step=self._index, n_steps=self._n)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        wall = time.perf_counter() - self._t0
        self._acct._tracer.end(self._span)
        self._acct._close_step(wall, self._n, self._attributed)
        return False

    def phase(self, name: str, **attrs):
        """`with st.phase("data_wait"):` — times the block, attributes it
        to `name`, and emits a tracer span `phase/<name>`."""
        if name not in self._acct.phase_totals:
            raise ValueError(f"unknown phase {name!r} (not in {PHASES})")
        return _Phase(self, name, attrs)


class _Phase:
    __slots__ = ("_step", "_name", "_t0", "_span")

    def __init__(self, step: _Step, name: str, attrs: dict):
        self._step = step
        self._name = name
        self._span = step._acct._tracer.begin(f"phase/{name}", **attrs) \
            if step._acct._tracer.enabled else None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        acct = self._step._acct
        acct._tracer.end(self._span)
        acct.phase_totals[self._name] += dt
        self._step._attributed += dt
        return False


class StepAccounting:
    """Accumulates per-step wall-clock samples + phase totals; summary()
    renders the done-event payload (percentiles + phase_breakdown)."""

    def __init__(self, tracer: "_tracer_mod.Tracer | None" = None):
        self._tracer = tracer if tracer is not None else _tracer_mod.get_tracer()
        # (per-step wall seconds, weight in steps) — one entry per step()
        # call, so a chunked loop stays O(chunks) however long the run.
        self.samples: list[tuple[float, int]] = []
        self.phase_totals: dict[str, float] = {p: 0.0 for p in PHASES}
        self.wall_s = 0.0

    def step(self, index: int, n_steps: int = 1) -> _Step:
        if n_steps < 1:
            raise ValueError("n_steps must be >= 1")
        return _Step(self, index, n_steps)

    def _close_step(self, wall: float, n_steps: int, attributed: float) -> None:
        # The residual telescopes by construction; clock granularity can
        # put attributed a hair over wall, so clamp at 0 rather than
        # emit a negative "other" (the overshoot is bounded by one
        # perf_counter quantum per phase).
        self.phase_totals["other"] += max(0.0, wall - attributed)
        self.samples.append((wall / n_steps, n_steps))
        self.wall_s += wall

    @property
    def steps(self) -> int:
        return sum(n for _, n in self.samples)

    def summary(self, digits: int = 6) -> dict | None:
        """Done-event payload: {"step_time_s": {p50,p95,p99,max,mean},
        "phase_breakdown": {wall_s, steps, <phase>: seconds...}} — the
        phase entries (including "other") sum to wall_s exactly, so a
        reader can telescope the distribution back to the measured
        wall-clock. None when no steps were recorded."""
        n = self.steps
        if n == 0:
            return None
        dist = {k: round(weighted_percentile(self.samples, q), digits)
                for k, q in QUANTILES}
        dist["max"] = round(max(w for w, _ in self.samples), digits)
        dist["mean"] = round(self.wall_s / n, digits)
        breakdown = {"wall_s": round(self.wall_s, digits), "steps": n}
        for p in PHASES:
            v = self.phase_totals[p]
            if v > 0.0 or p == "other":
                breakdown[p] = round(v, digits)
        return {"step_time_s": dist, "phase_breakdown": breakdown}


class NullStepAccounting:
    """Same surface, no clocks, no state: the TPUJOB_TELEMETRY=off path
    and the un-instrumented baseline for the overhead guard test."""

    samples: list = []
    phase_totals: dict = {}
    wall_s = 0.0
    steps = 0

    def step(self, index: int, n_steps: int = 1):
        return _NULL_CTX

    def summary(self, digits: int = 6) -> None:
        return None


def make_step_accounting(tracer=None):
    """StepAccounting, or the no-op variant when TPUJOB_TELEMETRY=off."""
    if os.environ.get("TPUJOB_TELEMETRY", "").lower() in ("off", "0", "false"):
        return NullStepAccounting()
    return StepAccounting(tracer)


def weighted_percentile(samples: list[tuple[float, int]], q: float) -> float:
    """Nearest-rank percentile over weighted samples: (value, weight) with
    integer weights is the exact expansion of `weight` copies of `value`
    (how one chunk of N steps contributes N per-step samples) without
    materializing the expansion."""
    if not samples:
        raise ValueError("no samples")
    if not 0.0 < q <= 1.0:
        raise ValueError(f"q must be in (0, 1], got {q}")
    ordered = sorted(samples)
    total = sum(w for _, w in ordered)
    rank = max(1, math.ceil(q * total))  # 1-based nearest-rank
    seen = 0
    for v, w in ordered:
        seen += w
        if seen >= rank:
            return v
    return ordered[-1][0]
