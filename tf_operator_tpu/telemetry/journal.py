"""Per-job lifecycle journal: the control plane's flight recorder.

The Tracer (tracer.py) answers "where did the wall clock go inside one
process"; the journal answers "WHY did this job take 90 s to admit" — a
bounded, thread-safe ring of structured lifecycle events per job that
the trainjob controller, serve controller, FleetScheduler,
SliceAllocator, and StatusWriter all record into: submit, validate,
queue enter/exit (with the blocking reason — quota vs capacity vs aging
rank), slice admit/release/upgrade, pod create/delete, condition
transitions, gang-roll and reshape decisions, the preemption latch
write→delete ordering, and status-flush outcomes
(sent/noop/deferred/fenced). Each event is stamped with the sync wave's
`reconcile_id`, so causality across subsystems reconstructs from one
stream.

Design constraints (the Tracer's, re-applied at fleet depth):

  1. **O(1) per event, no allocation beyond the tuple.** `record()` on
     the hot reconcile path is one lock, one deque append, one LRU
     move-to-end — no per-event dict, no string formatting, no clock
     math. The fleet bench (tools/exp_fleet.py) runs with the journal ON
     by default and its p99/writes-per-job gates pin the overhead.
  2. **Bounded memory at 10k jobs.** Per-job rings are
     collections.deque(maxlen=per_job_capacity); the job table itself is
     an LRU (OrderedDict) capped at max_jobs — churning 10k jobs through
     a 1k-entry journal evicts the coldest rings whole. `dropped(key)`
     is exact per ring (append + counter move under one lock, the
     Tracer's locked-append lesson), and `evicted_jobs` counts whole
     rings lost to LRU.
  3. **Post-mortem readable.** A deleted job's ring SURVIVES for
     `retention_s` (default 10 min) so `tpujob timeline` works on a job
     that already finished and was GC'd — `mark_deleted` stamps the ring
     instead of dropping it; expiry happens lazily on later writes.
  4. **Monotonic clocks, wall-clock anchored.** Events carry
     time.perf_counter_ns(); the journal records ONE (epoch_wall,
     epoch_ns) anchor at construction so exports can place events on the
     wall clock (to merge with trainer telemetry) without per-event
     time.time() calls or NTP-step artifacts inside a timeline.

Event-name vocabulary (docs/monitoring.md "Flight recorder" documents
the schema): ``submit`` ``validate`` ``queue.enter`` ``queue.blocked``
``queue.exit``
``slice.admit`` ``slice.release`` ``slice.upgrade`` ``pod.create``
``pod.delete`` ``condition`` ``gang.roll`` ``reshape``
``preempt.latch`` ``preempt.requeue`` ``status.flush`` ``deleted``
``router.open`` ``router.close`` ``router.failover`` ``router.hedge``
(the serve controller's front-end tier lifecycle; hedge resolutions
arrive from router handler threads, so they carry no reconcile wave).
"""

from __future__ import annotations

import collections
import threading
import time

__all__ = [
    "Journal", "JobRing", "get_journal", "configure", "phase_breakdown",
    "timeline_payload",
]


class JobRing:
    """One job's event ring + exact drop accounting. Internal mutable
    state is only touched under the owning Journal's lock."""

    __slots__ = ("events", "appended", "first_ns", "deleted_at_ns")

    def __init__(self, capacity: int):
        # (event, t_ns, reconcile_id, attrs) tuples; attrs is the kwargs
        # dict or None — the only per-event allocations are the tuple
        # and the caller's kwargs.
        self.events: collections.deque = collections.deque(maxlen=capacity)
        self.appended = 0
        self.first_ns = 0  # t_ns of the FIRST event ever (survives ring wrap)
        self.deleted_at_ns = 0  # 0 = live; else when mark_deleted stamped it

    @property
    def dropped(self) -> int:
        return max(0, self.appended - len(self.events))


class Journal:
    def __init__(
        self,
        per_job_capacity: int = 256,
        max_jobs: int = 4096,
        retention_s: float = 600.0,
        enabled: bool = True,
    ):
        if per_job_capacity < 1 or max_jobs < 1:
            raise ValueError("per_job_capacity and max_jobs must be >= 1")
        self.enabled = enabled
        self.per_job_capacity = per_job_capacity
        self.max_jobs = max_jobs
        self.retention_s = retention_s
        self._rings: collections.OrderedDict[str, JobRing] = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        # Per-thread current sync wave: the controller mints one
        # reconcile_id per sync (core/controller.py _process_item) and
        # every event recorded on that thread during the wave — by the
        # controller, the scheduler it consults, or the StatusWriter it
        # flushes through — is stamped with it without threading an id
        # through every call signature.
        self._wave = threading.local()
        self.evicted_jobs = 0  # whole rings lost to the LRU cap
        # Wall-clock anchor: t_wall = epoch_wall + (t_ns - epoch_ns)/1e9.
        self._epoch_ns = time.perf_counter_ns()
        self._epoch_wall = time.time()

    # ------------------------------------------------------------ recording

    def set_wave(self, reconcile_id: int) -> None:
        """Stamp this thread's subsequent records with `reconcile_id`
        (one sync wave = one id; 0 clears)."""
        self._wave.rid = reconcile_id

    def record(self, key: str, event: str, /, reconcile_id: int = 0,
               **attrs) -> None:
        """Append one event to `key`'s ring. O(1): lock, LRU touch,
        deque append. The disabled path is one attribute read."""
        if not self.enabled:
            return
        if not reconcile_id:
            reconcile_id = getattr(self._wave, "rid", 0)
        t_ns = time.perf_counter_ns()
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = JobRing(self.per_job_capacity)
                ring.first_ns = t_ns
                self._rings[key] = ring
                if len(self._rings) > self.max_jobs:
                    self._rings.popitem(last=False)
                    self.evicted_jobs += 1
            else:
                self._rings.move_to_end(key)
            ring.events.append((event, t_ns, reconcile_id, attrs or None))
            ring.appended += 1

    def mark_deleted(self, key: str) -> None:
        """The job object is gone; keep its ring for retention_s so a
        post-mortem `tpujob timeline` still reconstructs it. Lazily
        expires OTHER overdue rings on the way (no GC thread)."""
        if not self.enabled:
            return
        t_ns = time.perf_counter_ns()
        with self._lock:
            ring = self._rings.get(key)
            if ring is not None:
                ring.events.append(("deleted", t_ns, 0, None))
                ring.appended += 1
                ring.deleted_at_ns = t_ns
            if self.retention_s <= 0:
                self._rings.pop(key, None)
                return
            horizon = t_ns - int(self.retention_s * 1e9)
            expired = [k for k, r in self._rings.items()
                       if r.deleted_at_ns and r.deleted_at_ns < horizon]
            for k in expired:
                del self._rings[k]

    def forget(self, key: str) -> None:
        """Drop a ring immediately (tests / explicit purge)."""
        with self._lock:
            self._rings.pop(key, None)

    # ------------------------------------------------------------ inspection

    def __len__(self) -> int:
        with self._lock:
            return len(self._rings)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._rings

    def dropped(self, key: str) -> int:
        with self._lock:
            ring = self._rings.get(key)
            return ring.dropped if ring is not None else 0

    def wall_time(self, t_ns: int) -> float:
        """Place a journal timestamp on the wall clock (one anchor, no
        per-event time.time() — NTP steps cannot reorder a timeline)."""
        return self._epoch_wall + (t_ns - self._epoch_ns) / 1e9

    def elapsed_s(self, t0_ns: int, t1_ns: int) -> float:
        return (t1_ns - t0_ns) / 1e9

    def events(self, key: str) -> list[tuple]:
        """Snapshot of `key`'s events, oldest first, as raw
        (event, t_ns, reconcile_id, attrs) tuples."""
        with self._lock:
            ring = self._rings.get(key)
            return list(ring.events) if ring is not None else []

    def last_ts(self, key: str, event: str, **match) -> int | None:
        """t_ns of the most recent `event` in the ring (None if absent),
        optionally also matching attr values (e.g. type="Running").
        O(ring); called only on rare transitions, never per record."""
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                return None
            for name, t_ns, _rid, attrs in reversed(ring.events):
                if name != event:
                    continue
                if match and not (attrs and all(
                        attrs.get(k) == v for k, v in match.items())):
                    continue
                return t_ns
        return None

    def first_ts(self, key: str) -> int | None:
        """t_ns of the very first event recorded for the job — survives
        ring wrap (the submit anchor for time-to-X math)."""
        with self._lock:
            ring = self._rings.get(key)
            return ring.first_ns if ring is not None else None

    def export(self, key: str) -> dict | None:
        """The ring as a JSON-ready dict: wall-clock-anchored events plus
        drop/retention accounting. None when the job was never journaled
        (or already expired)."""
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                return None
            events = list(ring.events)
            dropped = ring.dropped
            first_ns = ring.first_ns
            deleted_ns = ring.deleted_at_ns
        out_events = []
        for name, t_ns, rid, attrs in events:
            ev = {
                "event": name,
                "t": round(self.wall_time(t_ns), 6),
                "offset_s": round((t_ns - first_ns) / 1e9, 6),
            }
            if rid:
                ev["reconcile_id"] = rid
            if attrs:
                ev["attrs"] = attrs
            out_events.append(ev)
        return {
            "job": key,
            "events": out_events,
            "dropped": dropped,
            "submitted_at": round(self.wall_time(first_ns), 6),
            "deleted": bool(deleted_ns),
        }

    def snapshot(self) -> dict:
        """Journal-wide accounting for /debug/state."""
        with self._lock:
            return {
                "jobs": len(self._rings),
                "max_jobs": self.max_jobs,
                "per_job_capacity": self.per_job_capacity,
                "retention_s": self.retention_s,
                "evicted_jobs": self.evicted_jobs,
                "events": sum(len(r.events) for r in self._rings.values()),
                "dropped": sum(r.dropped for r in self._rings.values()),
            }


def phase_breakdown(events: list[dict]) -> list[dict]:
    """Partition an exported event stream (Journal.export's `events`)
    into contiguous lifecycle phases. The segments tile the interval
    from the first event to the terminal event exactly — no gaps, no
    overlap — so their durations sum to the job's journaled wall clock
    (the `tpujob timeline` telescoping property its e2e test pins).

    Phases: ``queued`` (submit -> slice admitted, and again after a
    preemption requeue), ``startup`` (slice admitted -> Running/first
    trainer step), ``running``, ``recovery`` (gang roll or preemption
    latch -> Running re-asserted), ``terminal`` (a closed zero-width
    marker once Succeeded/Failed lands or the job is deleted)."""
    if not events:
        return []
    segs: list[dict] = []
    phase = "queued"
    start = events[0]["t"]

    def close(t: float, nxt: str) -> None:
        nonlocal phase, start
        if t > start:
            segs.append({"phase": phase, "start": round(start, 6),
                         "end": round(t, 6),
                         "seconds": round(t - start, 6)})
        phase, start = nxt, t

    for ev in events:
        name = ev["event"]
        t = ev["t"]
        attrs = ev.get("attrs") or {}
        if phase == "terminal":
            break
        if name == "slice.admit" and phase == "queued":
            close(t, "startup")
        elif name == "first_step" and phase == "startup":
            close(t, "running")
        elif (name == "condition" and attrs.get("type") == "Running"
              and attrs.get("status")
              and phase in ("queued", "startup", "recovery")):
            # `queued` included: a scheduler-less deployment journals no
            # slice.admit, so Running asserting IS the admission edge.
            close(t, "running")
        elif (name in ("gang.roll", "preempt.latch")
              and phase in ("running", "startup")):
            close(t, "recovery")
        elif name == "preempt.requeue" and phase == "recovery":
            close(t, "queued")
        elif (name == "condition" and attrs.get("status")
              and attrs.get("type") in ("Succeeded", "Failed")):
            close(t, "terminal")
        elif name == "deleted":
            close(t, "terminal")
    if phase != "terminal":
        close(events[-1]["t"], "terminal")
    return segs


def timeline_payload(namespace: str, name: str, *, telemetry=None,
                     journal: "Journal | None" = None) -> dict | None:
    """The full `tpujob timeline` payload for one job: the exported
    journal plus its phase breakdown, with the trainer-side telemetry
    (collector summaries) merged in when a collector is wired. The one
    assembly both the operator's /timeline route and LocalSession share.
    None when the job was never journaled (or its ring expired)."""
    jrnl = journal if journal is not None else get_journal()
    data = jrnl.export(f"{namespace}/{name}")
    if data is None:
        return None
    phases = phase_breakdown(data["events"])
    data["phases"] = phases
    data["wall_clock_s"] = round(sum(p["seconds"] for p in phases), 6)
    if telemetry is not None:
        data["trainer"] = telemetry.job_telemetry(namespace, name)
    return data


# Module-level default journal, mirroring tracer.get_tracer(): the
# zero-wiring path — controllers/scheduler/StatusWriter record into the
# process default unless a Journal is injected explicitly (tests inject).
_DEFAULT = Journal()


def get_journal() -> Journal:
    return _DEFAULT


def configure(enabled: bool | None = None, per_job_capacity: int | None = None,
              max_jobs: int | None = None,
              retention_s: float | None = None) -> Journal:
    """Configure the default journal (operator flags land here). Sizing
    changes re-allocate the table, dropping recorded rings — configure
    before the controllers start."""
    global _DEFAULT
    resize = (
        (per_job_capacity is not None
         and per_job_capacity != _DEFAULT.per_job_capacity)
        or (max_jobs is not None and max_jobs != _DEFAULT.max_jobs)
    )
    if resize:
        _DEFAULT = Journal(
            per_job_capacity=per_job_capacity or _DEFAULT.per_job_capacity,
            max_jobs=max_jobs or _DEFAULT.max_jobs,
            retention_s=(retention_s if retention_s is not None
                         else _DEFAULT.retention_s),
            enabled=_DEFAULT.enabled,
        )
    if retention_s is not None:
        _DEFAULT.retention_s = retention_s
    if enabled is not None:
        _DEFAULT.enabled = enabled
    return _DEFAULT
