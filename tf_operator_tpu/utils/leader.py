"""Leader election: single-host file lock + cluster-grade Lease election.

Two implementations of the reference's Endpoints-lock leader election
(app/server.go:157-182, 15s lease / 5s renew / 3s retry):

  LeaderElector — fcntl file lock. Multiple operator processes on ONE host
  serialize on a lock file; the kernel releases it on process exit, so
  failover is immediate. Used by the local-substrate deployment.

  LeaseElector — a coordination.k8s.io/v1 Lease through the API server.
  N operator replicas across nodes serialize cluster-wide; the loser waits
  as a hot standby and takes over once the holder's lease expires. Every
  write carries the lease's resourceVersion, so two contenders racing for
  an expired lease produce exactly one winner (the loser sees 409 Conflict
  and goes back to waiting). Used by the --kube-api / --in-cluster
  deployment; same 15s/5s/3s timing defaults as the reference.
"""

from __future__ import annotations

import fcntl
import os
import threading
import time
from datetime import datetime, timezone
from typing import Callable

from tf_operator_tpu.status import metrics
from tf_operator_tpu.utils.logging import FieldLogger

DEFAULT_LOCK_PATH = "/tmp/tpujob-operator.lock"
LEASE_API = "coordination.k8s.io/v1"


class LeaderElector:
    def __init__(self, lock_path: str = DEFAULT_LOCK_PATH, identity: str | None = None):
        self.lock_path = lock_path
        self.identity = identity or f"pid-{os.getpid()}"
        self._fd: int | None = None
        self._log = FieldLogger({"component": "leader-election", "id": self.identity})

    def try_acquire(self) -> bool:
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(fd, self.identity.encode())
        self._fd = fd
        metrics.is_leader.set(1)
        return True

    def run_or_die(
        self,
        on_started_leading: Callable[[], None],
        stop: threading.Event,
        retry_period: float = 3.0,
    ) -> None:
        """Block until leadership is acquired, then run the callback
        (leaderelection.RunOrDie shape, server.go:170)."""
        while not stop.is_set():
            if self.try_acquire():
                self._log.info("became leader")
                try:
                    on_started_leading()
                finally:
                    self.release()
                return
            self._log.info("waiting for leadership")
            stop.wait(retry_period)

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
            metrics.is_leader.set(0)


def _rfc3339(t: float) -> str:
    return (
        datetime.fromtimestamp(t, tz=timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


def _parse_rfc3339(v) -> float | None:
    # Same tolerance as the adapter's codec (floats from the fake server,
    # RFC3339 with Z from a real one).
    from tf_operator_tpu.core.k8s import _parse_time

    return _parse_time(v)


class LeaseElector:
    """Cluster-grade leader election on a coordination.k8s.io/v1 Lease.

    Semantics match the reference's resource-lock election
    (app/server.go:157-182): lease_duration 15s, renew every 5s, contenders
    retry every 3s. A leader that cannot renew for a full lease_duration
    considers itself deposed and calls on_lost (the RunOrDie contract — the
    operator process exits and its pod restarts as a standby).
    """

    def __init__(
        self,
        api,  # core.k8s.K8sApi
        namespace: str = "default",
        name: str = "tpujob-operator",
        identity: str | None = None,
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        retry_period: float = 3.0,
        renew_deadline: float | None = None,
    ):
        self.api = api
        self.namespace = namespace
        self.name = name
        self.identity = identity or f"{os.uname().nodename}-pid-{os.getpid()}"
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        # The leader must depose itself STRICTLY before a standby can seize
        # the expired lease, or both run controllers concurrently
        # (client-go: RenewDeadline 10s < LeaseDuration 15s).
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None
            else lease_duration * 2.0 / 3.0
        )
        self.renew_deadline = min(self.renew_deadline, lease_duration * 0.9)
        # Skew tolerance: lease expiry is judged by how long WE have
        # observed the lease unchanged (local monotonic clock), never by
        # comparing the holder's wall-clock renewTime with ours
        # (client-go's observedTime pattern).
        self._observed: tuple[str, str] | None = None
        self._observed_at = 0.0
        self._log = FieldLogger(
            {"component": "lease-election", "id": self.identity}
        )

    # ------------------------------------------------------------- wire

    @property
    def _list_path(self) -> str:
        return f"/apis/{LEASE_API}/namespaces/{self.namespace}/leases"

    @property
    def _path(self) -> str:
        return f"{self._list_path}/{self.name}"

    def _get(self, timeout: float | None = None) -> dict | None:
        from tf_operator_tpu.core.cluster import NotFoundError

        try:
            return self.api.request(
                "GET", self._path, timeout=timeout or self.renew_deadline
            )
        except NotFoundError:
            return None

    def _spec(self, acquire_time: float, transitions: int) -> dict:
        now = time.time()
        return {
            "holderIdentity": self.identity,
            # Integer seconds on the wire (the real Lease schema), never 0:
            # a 0 would read back falsy and every contender would substitute
            # its OWN configured duration — expiry must come from the lease.
            "leaseDurationSeconds": max(1, int(round(self.lease_duration))),
            "acquireTime": _rfc3339(acquire_time),
            "renewTime": _rfc3339(now),
            "leaseTransitions": transitions,
        }

    # -------------------------------------------------------- election

    def try_acquire_or_renew(self, timeout: float | None = None) -> bool:
        """One election round: create the lease, renew our own, or take
        over an expired one. resourceVersion-guarded writes make a
        concurrent race produce exactly one winner. Never raises on API
        trouble — any error is 'not leader this round', so the callers'
        timing loops (renewal deposes after renew_deadline of failures)
        handle transient 500s and network blips uniformly. `timeout`
        bounds each HTTP request (default renew_deadline)."""
        from tf_operator_tpu.core.cluster import ApiError

        try:
            return self._acquire_or_renew_round(timeout)
        except (ApiError, OSError) as e:
            self._log.info("election round failed: %s", e)
            return False

    def _acquire_or_renew_round(self, timeout: float | None = None) -> bool:
        from tf_operator_tpu.core.cluster import ApiError

        timeout = timeout or self.renew_deadline
        lease = self._get(timeout)
        now = time.time()
        if lease is None:
            body = {
                "apiVersion": LEASE_API,
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": self._spec(acquire_time=now, transitions=0),
            }
            try:
                self.api.request("POST", self._list_path, body,
                                 timeout=timeout)
                return True
            except ApiError:
                return False  # lost the create race
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        raw_duration = spec.get("leaseDurationSeconds")
        duration = (float(raw_duration) if raw_duration is not None
                    else self.lease_duration)
        ours = holder == self.identity
        if not ours and holder:
            # Restart the local observation clock whenever the lease record
            # changes; it is "expired" only once WE have seen it unchanged
            # for its full duration. Immune to cross-node wall-clock skew.
            key = (holder, str(spec.get("renewTime")))
            mono = time.monotonic()
            if key != self._observed:
                self._observed = key
                self._observed_at = mono
            if mono - self._observed_at < duration:
                return False  # someone else holds a live lease
        transitions = int(spec.get("leaseTransitions") or 0)
        lease["spec"] = self._spec(
            acquire_time=now if not ours
            else _parse_rfc3339(spec.get("acquireTime")) or now,
            transitions=transitions if ours else transitions + 1,
        )
        try:
            # lease["metadata"]["resourceVersion"] rides along: a stale rv
            # (concurrent takeover) 409s and we go back to waiting.
            self.api.request("PUT", self._path, lease, timeout=timeout)
            return True
        except ApiError:
            return False

    def _renew_loop(self, renew_stop: threading.Event,
                    lost: threading.Event,
                    on_lost: Callable[[], None]) -> None:
        last_renew = time.monotonic()
        while True:
            if renew_stop.wait(self.renew_period):
                return
            # Depose at renew_deadline (< lease_duration). Each attempt's
            # HTTP timeout is capped by the REMAINING deadline budget, so a
            # hung API connection cannot push deposition past the point
            # where a partitioned-off standby could seize the lease
            # (observation-based takeover needs >= lease_duration).
            budget = self.renew_deadline - (time.monotonic() - last_renew)
            if budget > 0 and self.try_acquire_or_renew(
                timeout=max(0.5, budget)
            ):
                last_renew = time.monotonic()
            elif time.monotonic() - last_renew > self.renew_deadline:
                self._log.error("lost leadership (lease not renewed in %.0fs)",
                                self.renew_deadline)
                lost.set()
                metrics.is_leader.set(0)
                on_lost()
                return

    def run_or_die(
        self,
        on_started_leading: Callable[[], None],
        stop: threading.Event,
        on_lost: Callable[[], None] | None = None,
    ) -> bool:
        """Block until leadership is acquired, then run the callback while a
        background thread renews the lease. If the lease is lost mid-flight,
        on_lost fires (default: set `stop`, so the callback unwinds — the
        process then exits and restarts as a standby, like the reference
        operator's leaderelection.RunOrDie). Returns False when leadership
        was lost, True on clean shutdown."""
        while not stop.is_set():
            if self.try_acquire_or_renew():
                self._log.info("became leader")
                metrics.is_leader.set(1)
                lost = threading.Event()
                renew_stop = threading.Event()
                renewer = threading.Thread(
                    target=self._renew_loop,
                    args=(renew_stop, lost, on_lost or stop.set),
                    daemon=True, name="lease-renew",
                )
                renewer.start()
                try:
                    on_started_leading()
                finally:
                    metrics.is_leader.set(0)
                    # Stop the renewer BEFORE releasing: a renew round that
                    # lands after the release would re-hold the lease under
                    # this (exiting) identity and force the standby to wait
                    # out the full lease. If the renewer is wedged in an
                    # in-flight request past the join timeout, skip the
                    # release — expiry-based takeover is slow but safe.
                    renew_stop.set()
                    renewer.join(timeout=5.0)
                    self.release(
                        lost_already=lost.is_set() or renewer.is_alive()
                    )
                return not lost.is_set()
            self._log.info("waiting for leadership")
            stop.wait(self.retry_period)
        return True

    def release(self, lost_already: bool = False) -> None:
        """Give up the lease on clean shutdown so the standby takes over
        immediately instead of waiting out the lease."""
        if lost_already:
            return
        from tf_operator_tpu.core.cluster import ApiError

        lease = None
        try:
            lease = self._get()
        except (ApiError, OSError):
            return
        if lease is None:
            return
        spec = lease.get("spec") or {}
        if spec.get("holderIdentity") != self.identity:
            return
        spec["holderIdentity"] = ""
        spec["renewTime"] = None
        lease["spec"] = spec
        try:
            self.api.request("PUT", self._path, lease)
        except (ApiError, OSError):
            pass
