"""Leader election via an fcntl file lock.

Capability parity with the reference's Endpoints-lock leader election
(app/server.go:157-182, 15s lease / 5s renew / 3s retry): multiple operator
processes on one host serialize on a lock file; exactly one runs the
controllers, the rest block as hot standbys and take over when the leader
dies (the kernel releases the lock on process exit, so failover is
immediate — no lease timers needed for the single-host case).
"""

from __future__ import annotations

import fcntl
import os
import threading
import time
from typing import Callable

from tf_operator_tpu.status import metrics
from tf_operator_tpu.utils.logging import FieldLogger

DEFAULT_LOCK_PATH = "/tmp/tpujob-operator.lock"


class LeaderElector:
    def __init__(self, lock_path: str = DEFAULT_LOCK_PATH, identity: str | None = None):
        self.lock_path = lock_path
        self.identity = identity or f"pid-{os.getpid()}"
        self._fd: int | None = None
        self._log = FieldLogger({"component": "leader-election", "id": self.identity})

    def try_acquire(self) -> bool:
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        os.ftruncate(fd, 0)
        os.write(fd, self.identity.encode())
        self._fd = fd
        metrics.is_leader.set(1)
        return True

    def run_or_die(
        self,
        on_started_leading: Callable[[], None],
        stop: threading.Event,
        retry_period: float = 3.0,
    ) -> None:
        """Block until leadership is acquired, then run the callback
        (leaderelection.RunOrDie shape, server.go:170)."""
        while not stop.is_set():
            if self.try_acquire():
                self._log.info("became leader")
                try:
                    on_started_leading()
                finally:
                    self.release()
                return
            self._log.info("waiting for leadership")
            stop.wait(retry_period)

    def release(self) -> None:
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
            metrics.is_leader.set(0)
