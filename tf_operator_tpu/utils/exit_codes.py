"""Exit-code restart policy table.

Capability parity with pkg/util/train/train_util.go:18-55: under
RestartPolicy.EXIT_CODE the operator restarts a replica only when its exit
code signals a transient condition.

  - 1..127 are "permanent" errors (app bug, bad image, OOM-kill by runtime):
    never retried — except 130/126+ signal range below.
  - 128+n means killed by signal n. SIGTERM(143)=128+15, SIGKILL(137)=128+9,
    SIGINT(130)=128+2 are infrastructure preemption/eviction: retryable.
    SIGSEGV(139)=128+11 is an app crash: permanent.
  - 138 = 128+SIGUSR1 is reserved as a *user-declared retryable* failure, so a
    workload can request its own restart.
"""

from __future__ import annotations

RETRYABLE_EXIT_CODES = frozenset({130, 137, 138, 143})
PERMANENT_EXIT_CODES = frozenset({1, 2, 126, 127, 128, 139})

# 128+SIGUSR1: the workload ASKING for its own restart — numerically in the
# signal range but semantically an app-declared retryable, not an
# infrastructure kill (restart metrics label it exit_code, not preempt).
EXIT_USER_RETRYABLE = 138


def is_retryable_exit_code(exit_code: int) -> bool:
    if exit_code in RETRYABLE_EXIT_CODES:
        return True
    if exit_code in PERMANENT_EXIT_CODES:
        return False
    # Unknown 1..127: app-level error, permanent. Unknown 128+: signal, retry.
    return exit_code > 128


def is_signal_exit(exit_code: int) -> bool:
    return exit_code > 128


def signal_of(exit_code: int) -> int | None:
    return exit_code - 128 if exit_code > 128 else None
