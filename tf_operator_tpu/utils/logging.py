"""Structured, key-tagged logging for the control plane.

Capability parity with pkg/logger/logger.go:26-80: every log line carries the
job / replica-type / replica-index / uid it concerns so operator logs can be
filtered per job (the reference emits JSON for Stackdriver; we emit
logfmt-style by default and JSON when TPUJOB_LOG_JSON=1).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any

from tf_operator_tpu.utils.env import getenv_bool

_ROOT = logging.getLogger("tpujob")
_CONFIGURED = False


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_StructuredFormatter(json_mode=getenv_bool("TPUJOB_LOG_JSON", False)))
    _ROOT.addHandler(handler)
    _ROOT.setLevel(logging.INFO)
    _ROOT.propagate = False
    _CONFIGURED = True


class _StructuredFormatter(logging.Formatter):
    def __init__(self, json_mode: bool):
        super().__init__()
        self.json_mode = json_mode

    def format(self, record: logging.LogRecord) -> str:
        fields: dict[str, Any] = getattr(record, "fields", {}) or {}
        if self.json_mode:
            payload = {
                "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
                "level": record.levelname.lower(),
                "msg": record.getMessage(),
                **fields,
            }
            return json.dumps(payload, sort_keys=True)
        kv = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        return f"{record.levelname[0]} {record.getMessage()}" + (f"  [{kv}]" if kv else "")


class FieldLogger:
    """A logger bound to a fixed set of structured fields."""

    def __init__(self, fields: dict[str, Any]):
        _configure()
        self.fields = fields

    def _log(self, level: int, msg: str, *args: Any) -> None:
        _ROOT.log(level, msg % args if args else msg, extra={"fields": self.fields})

    def info(self, msg: str, *args: Any) -> None:
        self._log(logging.INFO, msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self._log(logging.WARNING, msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self._log(logging.ERROR, msg, *args)

    def debug(self, msg: str, *args: Any) -> None:
        self._log(logging.DEBUG, msg, *args)

    def with_fields(self, **extra: Any) -> "FieldLogger":
        return FieldLogger({**self.fields, **extra})


def logger_for_job(namespace: str, name: str, uid: str = "") -> FieldLogger:
    f: dict[str, Any] = {"job": f"{namespace}.{name}"}
    if uid:
        f["uid"] = uid
    return FieldLogger(f)


def logger_for_replica(namespace: str, name: str, rtype: str) -> FieldLogger:
    return FieldLogger({"job": f"{namespace}.{name}", "replica-type": rtype})


def logger_for_pod(namespace: str, pod_name: str) -> FieldLogger:
    return FieldLogger({"pod": f"{namespace}.{pod_name}"})


def logger_for_key(key: str) -> FieldLogger:
    return FieldLogger({"job": key.replace("/", ".")})
