"""Roofline attribution from an XProf trace.

The reference delegates per-pod utilization to cAdvisor/prometheus queries
(docs/monitoring/README.md:1-60) and publishes no efficiency accounting at
all (SURVEY.md §6). On TPU, "percent of MXU peak" (MFU) is the wrong
efficiency metric for bandwidth-bound workloads (conv training lives on the
HBM roofline, not the matmul one), so the bench reports *which roofline the
workload sits on and how close it is* — parsed from the same XProf traces
the trainer's --profile-dir already writes.

Parsing goes through the xprof/tensorboard-plugin-profile "hlo_stats" tool
(per-HLO self time, bound-by classification, achieved HBM bandwidth). All
failures degrade to None: profiling is diagnostic, never load-bearing.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any


def _load_hlo_stats(xplane_paths: list[str]) -> list[dict[str, Any]] | None:
    try:
        from xprof.convert import raw_to_tool_data as r2t
    except Exception:
        try:
            from tensorboard_plugin_profile.convert import (  # type: ignore
                raw_to_tool_data as r2t,
            )
        except Exception:
            return None
    try:
        out, _ = r2t.xspace_to_tool_data(xplane_paths, "hlo_stats", {})
        data = json.loads(out) if isinstance(out, (str, bytes)) else out
        cols = [c["label"] for c in data["cols"]]
        return [
            dict(zip(cols, [c.get("v") for c in row["c"]]))
            for row in data["rows"]
        ]
    except Exception:
        return None


# Pallas kernels lower to HLO custom-calls that carry no cost metadata, so
# xprof cannot place them on a roofline and reports bound_by=Unknown — in
# round 4 that left 20% of the sparse-MoE step "Unknown" when every one of
# those ops was the in-repo flash-attention kernel (tools/exp_moe_attrib.py
# measured the bucket as 44 `attn.*` custom-calls and nothing else). Known
# in-repo kernels are therefore reclassified by op-name match, with the
# bound derived analytically: flash attention streams K/V once per q-block
# and keeps [block_q, block_k] score tiles in VMEM, so HBM bytes are
# O(T*H)/head while FLOPs are O(T^2*H)/head — arithmetic intensity ~T/2
# (>=1024 at bench seq lengths), far above the v5e ridge point
# (~240 FLOPs/byte at 197 TF/s / 819 GB/s): compute-bound by construction.
_KNOWN_PALLAS_PREFIXES = (
    ("attn", "Compute (pallas flash-attn)"),
    ("flash", "Compute (pallas flash-attn)"),
)


def _classify_custom_kernel(name: str) -> str | None:
    for prefix, label in _KNOWN_PALLAS_PREFIXES:
        if name.startswith(prefix):
            return label
    return None


def _bound_of(row: dict) -> str:
    """xprof's bound-by label, with Unknown custom-calls reclassified
    against the known-pallas-kernel table. Scoped to custom-call rows:
    pallas kernels lower to custom-calls, and an attn-named fusion that
    xprof genuinely could not place must stay Unknown."""
    b = str(row.get("Bound by") or "Unknown")
    if b == "Unknown" and "custom" in str(
            row.get("HLO op category") or "").lower():
        b = _classify_custom_kernel(
            str(row.get("HLO op name") or "")) or "Unknown"
    return b


def summarize_trace(trace_dir: str, top_k: int = 5) -> dict[str, Any] | None:
    """Roofline summary of every xplane.pb under trace_dir, or None.

    Returns {total_self_time_us, bound_by_pct: {HBM, Compute, ...},
    hbm_bound_achieved_bw_gibps (self-time-weighted mean over HBM-bound
    ops), top_ops: [{name, category, pct, bound_by, gflops, bw_gibps}]}.
    """
    try:
        return _summarize(trace_dir, top_k)
    except Exception:
        return None  # diagnostics only — any surprise degrades to None


def _summarize(trace_dir: str, top_k: int) -> dict[str, Any] | None:
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not paths:
        return None
    rows = _load_hlo_stats(paths)
    if not rows:
        return None

    t_key = "Total self time (us)"
    total = sum(r.get(t_key) or 0 for r in rows)
    if total <= 0:
        return None

    bound: dict[str, float] = {}
    bw_weight = bw_time = 0.0
    for r in rows:
        t = r.get(t_key) or 0
        b = _bound_of(r)
        bound[b] = bound.get(b, 0.0) + t
        if b == "HBM" and r.get("HBM BW (GiB/s)"):
            bw_weight += t * float(r["HBM BW (GiB/s)"])
            bw_time += t

    rows.sort(key=lambda r: -(r.get(t_key) or 0))
    top = [
        {
            "name": r.get("HLO op name"),
            "category": r.get("HLO op category"),
            "pct": round((r.get(t_key) or 0) / total * 100, 1),
            "bound_by": _bound_of(r),
            "gflops": r.get("Model GFLOP/s"),
            "bw_gibps": r.get("HBM BW (GiB/s)"),
        }
        for r in rows[:top_k]
    ]
    return {
        "total_self_time_us": round(total, 1),
        "bound_by_pct": {
            k: round(v / total * 100, 1) for k, v in
            sorted(bound.items(), key=lambda kv: -kv[1])
        },
        "hbm_bound_achieved_bw_gibps": (
            round(bw_weight / bw_time, 1) if bw_time else None
        ),
        "top_ops": top,
    }
