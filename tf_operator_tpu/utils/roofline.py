"""Roofline attribution from an XProf trace.

The reference delegates per-pod utilization to cAdvisor/prometheus queries
(docs/monitoring/README.md:1-60) and publishes no efficiency accounting at
all (SURVEY.md §6). On TPU, "percent of MXU peak" (MFU) is the wrong
efficiency metric for bandwidth-bound workloads (conv training lives on the
HBM roofline, not the matmul one), so the bench reports *which roofline the
workload sits on and how close it is* — parsed from the same XProf traces
the trainer's --profile-dir already writes.

Parsing goes through the xprof/tensorboard-plugin-profile "hlo_stats" tool
(per-HLO self time, bound-by classification, achieved HBM bandwidth). All
failures degrade to None: profiling is diagnostic, never load-bearing.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any


def _load_hlo_stats(xplane_paths: list[str]) -> list[dict[str, Any]] | None:
    try:
        from xprof.convert import raw_to_tool_data as r2t
    except Exception:
        try:
            from tensorboard_plugin_profile.convert import (  # type: ignore
                raw_to_tool_data as r2t,
            )
        except Exception:
            return None
    try:
        out, _ = r2t.xspace_to_tool_data(xplane_paths, "hlo_stats", {})
        data = json.loads(out) if isinstance(out, (str, bytes)) else out
        cols = [c["label"] for c in data["cols"]]
        return [
            dict(zip(cols, [c.get("v") for c in row["c"]]))
            for row in data["rows"]
        ]
    except Exception:
        return None


def summarize_trace(trace_dir: str, top_k: int = 5) -> dict[str, Any] | None:
    """Roofline summary of every xplane.pb under trace_dir, or None.

    Returns {total_self_time_us, bound_by_pct: {HBM, Compute, ...},
    hbm_bound_achieved_bw_gibps (self-time-weighted mean over HBM-bound
    ops), top_ops: [{name, category, pct, bound_by, gflops, bw_gibps}]}.
    """
    try:
        return _summarize(trace_dir, top_k)
    except Exception:
        return None  # diagnostics only — any surprise degrades to None


def _summarize(trace_dir: str, top_k: int) -> dict[str, Any] | None:
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
    )
    if not paths:
        return None
    rows = _load_hlo_stats(paths)
    if not rows:
        return None

    t_key = "Total self time (us)"
    total = sum(r.get(t_key) or 0 for r in rows)
    if total <= 0:
        return None

    bound: dict[str, float] = {}
    bw_weight = bw_time = 0.0
    for r in rows:
        t = r.get(t_key) or 0
        b = str(r.get("Bound by") or "Unknown")
        bound[b] = bound.get(b, 0.0) + t
        if b == "HBM" and r.get("HBM BW (GiB/s)"):
            bw_weight += t * float(r["HBM BW (GiB/s)"])
            bw_time += t

    rows.sort(key=lambda r: -(r.get(t_key) or 0))
    top = [
        {
            "name": r.get("HLO op name"),
            "category": r.get("HLO op category"),
            "pct": round((r.get(t_key) or 0) / total * 100, 1),
            "bound_by": r.get("Bound by"),
            "gflops": r.get("Model GFLOP/s"),
            "bw_gibps": r.get("HBM BW (GiB/s)"),
        }
        for r in rows[:top_k]
    ]
    return {
        "total_self_time_us": round(total, 1),
        "bound_by_pct": {
            k: round(v / total * 100, 1) for k, v in
            sorted(bound.items(), key=lambda kv: -kv[1])
        },
        "hbm_bound_achieved_bw_gibps": (
            round(bw_weight / bw_time, 1) if bw_time else None
        ),
        "top_ops": top,
    }
