"""Persistent XLA compilation cache shared by every pod process.

The reference operator compiles nothing (all math lives in user containers,
SURVEY.md §0); a TPU-native data plane, by contrast, pays XLA's first
compile (~20-40s on a v5e chip) in EVERY pod process unless compiled
programs persist. Pointing `jax_compilation_cache_dir` at one on-disk
directory makes an N-replica job compile each program once per machine
instead of once per pod, and drops pod-startup->first-step latency from
tens of seconds to seconds on every subsequent run of the same program
shape (the north-star latency metric, BASELINE.md).

Set TPUJOB_COMPILE_CACHE to a directory to relocate the cache, or to
"off" to disable; unset uses ~/.cache/tpujob/xla.
"""

from __future__ import annotations

import os

ENV_COMPILE_CACHE = "TPUJOB_COMPILE_CACHE"
_DISABLED = ("off", "0", "none", "disabled")


def default_cache_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache", "tpujob", "xla")


def enable_compile_cache(path: str | None = None) -> str | None:
    """Enable the persistent compilation cache; returns the directory in
    use, or None when disabled (TPUJOB_COMPILE_CACHE=off) or unavailable.
    Call after `import jax` and before the first jit compilation."""
    resolved = path if path is not None else os.environ.get(ENV_COMPILE_CACHE)
    if resolved is None:
        resolved = default_cache_dir()
    if not resolved or resolved.lower() in _DISABLED:
        return None
    try:
        os.makedirs(resolved, exist_ok=True)
    except OSError:
        return None
    import jax

    try:
        # Cache everything: even sub-second compiles cost a round-trip to a
        # tunneled chip's compiler far exceeding a local disk read. The
        # thresholds go first and the dir last, so a partial failure leaves
        # the cache fully off (no dir == disabled), matching the None return.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", resolved)
    except (AttributeError, ValueError):
        return None  # older jax without these knobs: run uncached
    return resolved
