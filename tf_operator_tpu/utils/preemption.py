"""Graceful-preemption signal handling for the trainer.

The dominant real-world failure on TPU fleets is preemption/eviction:
the kubelet (or the cloud provider) delivers SIGTERM and SIGKILLs after
the pod's grace period. Without a handler, SIGTERM kills the trainer
mid-step and every step since the last periodic checkpoint is lost; with
this guard, the signal only sets a flag, the trainer finishes the
in-flight step at the next boundary, writes an emergency checkpoint if
the grace budget allows, emits a `preempted` event, and exits 128+signum
— exactly the exit codes utils/exit_codes.py classifies as retryable, so
the operator's EXIT_CODE restart policy brings the pod back and
auto-resume continues from the emergency checkpoint.

Signals handled:

    SIGTERM -> exit 143   infrastructure preemption/eviction (retryable)
    SIGINT  -> exit 130   operator/ctrl-C interruption       (retryable)
    SIGUSR1 -> exit 138   user-declared retryable restart request
                          (the code exit_codes.py reserves for exactly
                          this)

Only the FIRST signal is latched (a second SIGTERM during the grace
window must not re-enter teardown); the handler itself is async-signal
safe — it records (signum, monotonic time) and returns.

This module also carries the progress heartbeat (round 10): exit codes
can only report failures that EXIT. A job wedged in a dead collective is
Running forever as far as pod phases go, so the trainer additionally
writes a tiny monotonic `{step, t}` heartbeat file at step boundaries
(`TPUJOB_HEARTBEAT_FILE`, injected by the runtime like
`TPUJOB_METRICS_FILE`); the operator's hang watchdog
(`recovery.heartbeatTimeoutSeconds`) treats a stale heartbeat on a
Running job as a hang and gang-restarts it.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

HANDLED_SIGNALS = (signal.SIGTERM, signal.SIGINT, signal.SIGUSR1)

ENV_HEARTBEAT_FILE = "TPUJOB_HEARTBEAT_FILE"


class HeartbeatWriter:
    """Writes the trainer's progress heartbeat: `{"step": N, "t": <epoch>,
    "pid": ...}`, atomically (tmp + os.replace) so a reader never sees a
    torn JSON. `step` is monotonic within one process generation; `t` is
    wall-clock at write time — the watchdog's staleness clock.

    Throttled: boundaries closer together than `min_interval_s` skip the
    write (tiny models step thousands of times per second; hang timeouts
    are seconds-scale, so sub-second cadence buys nothing). With no path
    configured every call is a no-op — standalone runs pay one `is None`
    check. IO errors are swallowed: a full disk must degrade the liveness
    signal, never kill the training step that just completed.

    Thread-safe and step-monotonic: the async checkpoint writer
    force-writes the just-durable save's step from ITS thread (the
    durable-progress rule keys on write COMPLETION, not save initiation)
    while the step loop keeps writing boundary heartbeats — the lock
    serializes the tmp+replace pair, and a forced write whose step trails
    the boundary high-water refreshes `t` at the high-water instead of
    regressing `step` (the documented monotonic contract consumers like
    the tally-reset baseline rely on)."""

    def __init__(self, path: str | None, min_interval_s: float = 0.5):
        self.path = path or None
        self.min_interval_s = min_interval_s
        self._last_write = 0.0
        self._last_step = 0
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, env: dict | None = None) -> "HeartbeatWriter":
        e = os.environ if env is None else env
        return cls(e.get(ENV_HEARTBEAT_FILE))

    def write(self, step: int, force: bool = False) -> bool:
        """Record `step` as completed; True when a write actually landed."""
        if self.path is None:
            return False
        with self._lock:
            now = time.monotonic()
            if not force and now - self._last_write < self.min_interval_s:
                return False
            step = max(int(step), self._last_step)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump({"step": step, "t": time.time(),
                               "pid": os.getpid()}, f)
                os.replace(tmp, self.path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return False
            self._last_write = now
            self._last_step = step
            return True


def read_heartbeat(path: str) -> dict | None:
    """One pod's heartbeat, or None (absent/torn/not-yet-written). The
    writer's os.replace makes a torn read mean 'no heartbeat', which the
    watchdog treats as not-armed — the safe direction."""
    try:
        with open(path) as f:
            hb = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(hb, dict) or "t" not in hb:
        return None
    return hb


class PreemptionGuard:
    """Latches the first delivery of a handled signal; the training loop
    polls `triggered` at step boundaries."""

    def __init__(self) -> None:
        self._signum: int | None = None
        self._t: float | None = None
        self._saved: dict[int, object] = {}
        self.installed = False

    def install(self) -> bool:
        """Install handlers (main thread only — the interpreter rejects
        signal.signal elsewhere). Returns False when not installed; the
        trainer then runs exactly as before this feature existed. The
        displaced handlers are remembered for uninstall()."""
        if threading.current_thread() is not threading.main_thread():
            return False
        try:
            for sig in HANDLED_SIGNALS:
                self._saved[sig] = signal.signal(sig, self._handler)
        except (ValueError, OSError):
            self.uninstall()  # partial install: roll back what landed
            return False
        self.installed = True
        return True

    def reassert(self) -> bool:
        """Re-take the handled signals if a library displaced our handlers
        AFTER install(): jax.distributed.initialize constructs XLA's TSL
        PreemptionNotifier, whose own SIGTERM handler silently replaces
        the guard's — a multi-process trainer would then step straight
        through a graceful eviction (the notifier logs "SIGTERM caught"
        and nothing else happens) until the runtime's drain discipline
        SIGKILLs it, losing the emergency checkpoint. Call after any
        distributed init. The ORIGINALLY displaced handlers stay
        remembered, so uninstall() still restores the pre-guard world."""
        if (not self.installed
                or threading.current_thread() is not threading.main_thread()):
            return False
        try:
            for sig in HANDLED_SIGNALS:
                # `==`, not `is`: self._handler is a bound method, and
                # every attribute access builds a fresh wrapper object.
                if signal.getsignal(sig) != self._handler:
                    signal.signal(sig, self._handler)
        except (ValueError, OSError):
            return False
        return True

    def uninstall(self) -> None:
        """Restore the displaced handlers. An in-process caller of the
        trainer's main() (tests, notebooks) must get its SIGINT semantics
        back — a stale guard latching Ctrl-C would make the host process
        uninterruptible."""
        for sig, h in list(self._saved.items()):
            try:
                signal.signal(sig, h)
            except (ValueError, OSError, TypeError):
                pass
            del self._saved[sig]
        self.installed = False

    def _handler(self, signum, frame) -> None:
        if self._signum is None:  # latch the first signal only
            self._signum = signum
            self._t = time.monotonic()

    @property
    def triggered(self) -> bool:
        return self._signum is not None

    @property
    def signum(self) -> int | None:
        return self._signum

    @property
    def signal_name(self) -> str | None:
        if self._signum is None:
            return None
        try:
            return signal.Signals(self._signum).name
        except ValueError:
            return str(self._signum)

    @property
    def exit_code(self) -> int:
        """128+signum, the shell convention the operator's exit-code
        policy classifies (143/130/138 are all retryable)."""
        return 128 + (self._signum or signal.SIGTERM)

    def elapsed(self) -> float:
        """Seconds since the latched signal arrived (0.0 if none)."""
        return 0.0 if self._t is None else time.monotonic() - self._t

    def within_grace(self, est_save_s: float, grace_s: float) -> bool:
        """Would an emergency save of ~est_save_s still fit the grace
        budget? The budget is measured from signal receipt (the kubelet
        SIGKILLs grace_s after SIGTERM, whatever we are doing), so time
        already burned finishing the in-flight step counts against it —
        including seconds spent DRAINING an in-flight async checkpoint
        write before this call (the drain happens-before the fast-path
        decision, so it flows through elapsed() with no extra
        bookkeeping). grace_s <= 0 means no budget: never attempt the
        save."""
        if grace_s <= 0:
            return False
        return self.elapsed() + max(0.0, est_save_s) < grace_s
