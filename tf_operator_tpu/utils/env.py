"""Typed environment-variable helpers.

Capability parity with the fork's pkg/util/util.go:79-104 (Getenv /
GetenvInt32 / GetenvBool), which back its configurable TTL defaults.
"""

from __future__ import annotations

import os


def getenv(key: str, default: str = "") -> str:
    v = os.environ.get(key)
    return v if v not in (None, "") else default


def getenv_int(key: str, default: int) -> int:
    v = os.environ.get(key)
    if v in (None, ""):
        return default
    try:
        return int(v)
    except ValueError:
        return default


def getenv_bool(key: str, default: bool) -> bool:
    v = os.environ.get(key)
    if v in (None, ""):
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")
