"""Shared control-plane utilities (no JAX imports here)."""
