"""Deterministic naming for replicas, services, and expectation keys.

Capability parity with the reference's pkg/common/jobcontroller/util.go:24-56
(GenGeneralName / GenExpectationPodsKey / GenPodGroupName): the naming contract
`{job}-{replica-type}-{index}` is load-bearing — it is the DNS identity each
replica is addressed by in the injected cluster spec, and the reference pins it
with pod_names_validation_tests.py.
"""

from __future__ import annotations

import re

# K8s DNS-1035/1123 label constraints that names must satisfy.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
MAX_NAME_LEN = 63


def gen_general_name(job_name: str, replica_type: str, index: int | str) -> str:
    """`{job}-{type}-{index}`, lowercased, '/'-free (ref util.go:24-32)."""
    n = f"{job_name}-{replica_type}-{index}".lower()
    return n.replace("/", "-")


def gen_expectation_pods_key(job_key: str, replica_type: str) -> str:
    """Expectation-cache key for pod creations/deletions (ref util.go:46)."""
    return f"{job_key}/{replica_type.lower()}/pods"


def gen_expectation_services_key(job_key: str, replica_type: str) -> str:
    """Expectation-cache key for service creations (ref util.go:50)."""
    return f"{job_key}/{replica_type.lower()}/services"


def gen_podgroup_name(job_name: str) -> str:
    """PodGroup shares the job's name (ref util.go:54-56)."""
    return job_name


def job_key(namespace: str, name: str) -> str:
    """Workqueue key, `namespace/name` (client-go MetaNamespaceKeyFunc shape)."""
    return f"{namespace}/{name}" if namespace else name


def split_job_key(key: str) -> tuple[str, str]:
    """Inverse of job_key; returns (namespace, name)."""
    if "/" not in key:
        return "", key
    ns, name = key.split("/", 1)
    return ns, name


def is_valid_dns_name(name: str) -> bool:
    return bool(name) and len(name) <= MAX_NAME_LEN and _NAME_RE.match(name) is not None


def replica_index_from_name(pod_name: str) -> int | None:
    """Extract trailing `-{index}` from a replica pod name; None if absent."""
    m = re.search(r"-(\d+)$", pod_name)
    return int(m.group(1)) if m else None
