"""Gang scheduling: TPU slice topology model + PodGroup atomic acquisition."""
