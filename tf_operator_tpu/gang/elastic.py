"""Elastic gang reshaping: the replica/mesh arithmetic behind
`runPolicy.recovery.elastic`.

When a gang cannot re-place at full size (its slice class has no free —
or even existing — capacity), the controller may re-admit it onto a
SMALLER slice of the same accelerator with proportionally fewer worker
replicas, provided the shrink is exact: the worker count and the mesh's
data axis must both scale by the same integral factor, or the reshaped
job would build a mesh whose device product no longer matches its world
size. These helpers are pure functions so the validation matrix and the
controller share one definition of "reshapeable".

The topology-portable checkpoint layer (models/checkpoint.py sharding
manifests + the trainer's --allow-reshape resume) is what makes the
re-admitted gang RESUME rather than restart: the saved trainstate was
laid out for the old mesh, and restore re-lays-out every leaf onto
whatever mesh the reshaped gang builds.
"""

from __future__ import annotations

from tf_operator_tpu.gang.topology import parse_topology


def scaled_worker_count(
    full_workers: int, full_chips: int, granted_chips: int,
    min_replicas: int = 1,
) -> int | None:
    """Worker count for a gang reshaped from a `full_chips` slice onto a
    `granted_chips` one: proportional, and only when the scale is exact
    (2 workers on 2 chips -> 1 worker on 1 chip; 3 workers never fit a
    2/3 shrink). None when the shrink is not representable or would go
    below `min_replicas`."""
    if full_workers <= 0 or full_chips <= 0 or granted_chips <= 0:
        return None
    if granted_chips >= full_chips:
        return full_workers
    scaled = full_workers * granted_chips
    if scaled % full_chips:
        return None
    scaled //= full_chips
    if scaled < 1 or scaled < max(1, min_replicas):
        return None
    return scaled


def scaled_mesh_axes(
    axes: dict[str, int], full_workers: int, new_workers: int
) -> dict[str, int] | None:
    """Rescale a mesh's DATA axis for a gang going from `full_workers` to
    `new_workers` replicas. Only dp (then fsdp) may absorb the change —
    tp/sp/ep/pp shard model dimensions whose layout a replica-count change
    must not silently alter. Returns the new axes dict, the input axes
    unchanged when there is nothing to scale, or None when no data axis
    divides cleanly (the job is not reshapeable to that size)."""
    if new_workers == full_workers or not axes:
        return dict(axes) if axes else axes
    if full_workers <= 0 or new_workers <= 0:
        return None
    out = dict(axes)
    for ax in ("dp", "fsdp"):
        size = out.get(ax)
        if not size:
            continue
        scaled = size * new_workers
        if scaled % full_workers == 0 and scaled // full_workers >= 1:
            out[ax] = scaled // full_workers
            return out
    return None


def degraded_plan(
    full_topology: str, full_workers: int,
    granted_topology: str,
    mesh_axes: dict[str, int] | None,
    min_replicas: int = 1,
) -> tuple[int, dict[str, int] | None] | None:
    """Full reshape feasibility check for one candidate slice class:
    (scaled worker count, scaled mesh axes) or None when the gang cannot
    shrink onto `granted_topology` (non-integral replica scale, below
    minReplicas, or a mesh whose data axes cannot absorb the change)."""
    try:
        full = parse_topology(full_topology)
        granted = parse_topology(granted_topology)
    except ValueError:
        return None
    workers = scaled_worker_count(
        full_workers, full.num_chips, granted.num_chips, min_replicas
    )
    if workers is None:
        return None
    axes = mesh_axes or {}
    scaled_axes = scaled_mesh_axes(axes, full_workers, workers)
    if axes and scaled_axes is None:
        return None
    return workers, scaled_axes
