"""Gang scheduling: PodGroup sync + atomic TPU-slice admission.

Capability parity with the reference's kube-batch integration
(jobcontroller.go:226-258, pod.go:224-238): a PodGroup sized
minMember=ΣReplicas is created before pods, each pod carries the
`scheduling.k8s.io/group-name` annotation and the gang scheduler's name, and
the PodGroup is deleted when the job terminates.

TPU twist (SURVEY.md §2 gang row): a TPU slice is an inherently atomic unit —
you get the whole v5e-32 slice or nothing. `SliceAllocator` models a fleet of
slices and admits a PodGroup only when a whole slice matching the requested
topology is free, which is exactly the all-or-nothing placement kube-batch
provided for GPU pods, with the granularity raised from "pod fits on a node"
to "job fits on a slice". This prevents the partial-placement deadlock the
reference used gang scheduling to avoid.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tf_operator_tpu.api.types import ObjectMeta, TrainJob
from tf_operator_tpu.core.cluster import InMemoryCluster, PodGroup
from tf_operator_tpu.gang.topology import SliceTopology, parse_topology
from tf_operator_tpu.utils.naming import gen_podgroup_name

ANNOTATION_GROUP_NAME = "scheduling.k8s.io/group-name"
DEFAULT_GANG_SCHEDULER = "volcano"  # ref options.go default


def sync_podgroup(cluster: InMemoryCluster, job: TrainJob) -> PodGroup:
    """Create-or-update the job's PodGroup (ref SyncPodGroup:226)."""
    name = gen_podgroup_name(job.name)
    min_member = job.spec.run_policy.scheduling.min_available
    if min_member is None:
        min_member = job.total_replicas()
    existing = cluster.try_get_podgroup(job.namespace, name)
    if existing is not None:
        if existing.min_member != min_member:
            existing.min_member = min_member
            return cluster.update_podgroup(existing)
        return existing
    pg = PodGroup(
        metadata=ObjectMeta(
            name=name,
            namespace=job.namespace,
            labels={"job-name": job.name},
            owner_references=[],
        ),
        min_member=min_member,
        queue=job.spec.run_policy.scheduling.queue,
        priority_class=job.spec.run_policy.scheduling.priority_class,
        tpu_topology=job.spec.tpu.topology if job.spec.tpu else "",
    )
    return cluster.create_podgroup(pg)


def delete_podgroup(cluster: InMemoryCluster, job: TrainJob) -> bool:
    """Delete the job's PodGroup if present (ref DeletePodGroup:252)."""
    name = gen_podgroup_name(job.name)
    if cluster.try_get_podgroup(job.namespace, name) is None:
        return False
    cluster.delete_podgroup(job.namespace, name)
    return True


@dataclass
class SliceState:
    topology: SliceTopology
    slice_id: str
    held_by: str | None = None  # "{ns}/{podgroup}" when allocated


@dataclass
class SliceAllocator:
    """Atomic whole-slice admission control.

    The fleet is a set of slices (e.g. four v5e-32 slices). `admit` grants a
    PodGroup a whole free slice of the requested topology or rejects it —
    never a partial allocation. Thread-safe; idempotent per holder."""

    slices: list[SliceState] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @classmethod
    def of(cls, *topologies: str) -> "SliceAllocator":
        return cls(
            slices=[
                SliceState(topology=parse_topology(t), slice_id=f"slice-{i}")
                for i, t in enumerate(topologies)
            ]
        )

    def admit(self, holder: str, topology: str) -> str | None:
        """Returns a slice_id, or None when no whole slice is free."""
        want = parse_topology(topology)
        with self._lock:
            for s in self.slices:
                if s.held_by == holder:
                    return s.slice_id  # idempotent re-admission
            for s in self.slices:
                if (
                    s.held_by is None
                    and s.topology.accelerator == want.accelerator
                    and s.topology.num_chips == want.num_chips
                ):
                    s.held_by = holder
                    return s.slice_id
        return None

    def release(self, holder: str) -> bool:
        """Free the holder's slices; True if anything was actually held (so
        the controller can kick jobs waiting on slice admission instead of
        leaving them to the retry backoff)."""
        freed = False
        with self._lock:
            for s in self.slices:
                if s.held_by == holder:
                    s.held_by = None
                    freed = True
        return freed

    def free_slices(self) -> int:
        with self._lock:
            return sum(1 for s in self.slices if s.held_by is None)

    def free_by_class(self) -> dict[tuple[str, int], int]:
        """Free slice count per capacity class (accelerator, num_chips) —
        the granularity `admit` matches on. The fleet scheduler simulates
        reservations for higher-ranked waiters against this view."""
        out: dict[tuple[str, int], int] = {}
        with self._lock:
            for s in self.slices:
                if s.held_by is None:
                    k = (s.topology.accelerator, s.topology.num_chips)
                    out[k] = out.get(k, 0) + 1
        return out

def slice_class(topology: str) -> tuple[str, int]:
    """Capacity class of a topology request: (accelerator, chip count) —
    exactly the fields SliceAllocator.admit matches a free slice on."""
    t = parse_topology(topology)
    return (t.accelerator, t.num_chips)
