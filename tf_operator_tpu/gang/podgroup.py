"""Gang scheduling: PodGroup sync + atomic TPU-slice admission.

Capability parity with the reference's kube-batch integration
(jobcontroller.go:226-258, pod.go:224-238): a PodGroup sized
minMember=ΣReplicas is created before pods, each pod carries the
`scheduling.k8s.io/group-name` annotation and the gang scheduler's name, and
the PodGroup is deleted when the job terminates.

TPU twist (SURVEY.md §2 gang row): a TPU slice is an inherently atomic unit —
you get the whole v5e-32 slice or nothing. `SliceAllocator` models a fleet of
slices and admits a PodGroup only when a whole slice matching the requested
topology is free, which is exactly the all-or-nothing placement kube-batch
provided for GPU pods, with the granularity raised from "pod fits on a node"
to "job fits on a slice". This prevents the partial-placement deadlock the
reference used gang scheduling to avoid.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tf_operator_tpu.api.types import ObjectMeta, TrainJob
from tf_operator_tpu.core.cluster import InMemoryCluster, PodGroup
from tf_operator_tpu.gang.topology import SliceTopology, parse_topology
from tf_operator_tpu.utils.naming import gen_podgroup_name

ANNOTATION_GROUP_NAME = "scheduling.k8s.io/group-name"
DEFAULT_GANG_SCHEDULER = "volcano"  # ref options.go default


def sync_podgroup(cluster: InMemoryCluster, job: TrainJob) -> PodGroup:
    """Create-or-update the job's PodGroup (ref SyncPodGroup:226)."""
    name = gen_podgroup_name(job.name)
    min_member = job.spec.run_policy.scheduling.min_available
    if min_member is None:
        min_member = job.total_replicas()
    existing = cluster.try_get_podgroup(job.namespace, name)
    if existing is not None:
        if existing.min_member != min_member:
            existing.min_member = min_member
            return cluster.update_podgroup(existing)
        return existing
    pg = PodGroup(
        metadata=ObjectMeta(
            name=name,
            namespace=job.namespace,
            labels={"job-name": job.name},
            owner_references=[],
        ),
        min_member=min_member,
        queue=job.spec.run_policy.scheduling.queue,
        priority_class=job.spec.run_policy.scheduling.priority_class,
        tpu_topology=job.spec.tpu.topology if job.spec.tpu else "",
    )
    return cluster.create_podgroup(pg)


def delete_podgroup(cluster: InMemoryCluster, job: TrainJob) -> bool:
    """Delete the job's PodGroup if present (ref DeletePodGroup:252)."""
    name = gen_podgroup_name(job.name)
    if cluster.try_get_podgroup(job.namespace, name) is None:
        return False
    cluster.delete_podgroup(job.namespace, name)
    return True


@dataclass
class SliceState:
    topology: SliceTopology
    slice_id: str
    held_by: str | None = None  # "{ns}/{podgroup}" when allocated
    # Capacity loss (maintenance, node failure, a chaos `capacity:`
    # directive): an offline slice is invisible to fresh admission and to
    # free_by_class, but a HOLDER keeps it until its claim is released —
    # real slice loss kills the gang's pods anyway, so the controller
    # notices at the next gang roll (held_offline) rather than yanking a
    # healthy running gang out from under itself.
    offline: bool = False

    def matches(self, want: SliceTopology) -> bool:
        """Same capacity class as `want` — the ONE definition of what
        `admit` grants, `claim`/`upgrade` move between, and
        release_except_class keeps."""
        return (self.topology.accelerator == want.accelerator
                and self.topology.num_chips == want.num_chips)


@dataclass
class SliceAllocator:
    """Atomic whole-slice admission control.

    The fleet is a set of slices (e.g. four v5e-32 slices). `admit` grants a
    PodGroup a whole free slice of the requested topology or rejects it —
    never a partial allocation. Thread-safe; idempotent per holder."""

    slices: list[SliceState] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    @classmethod
    def of(cls, *topologies: str) -> "SliceAllocator":
        return cls(
            slices=[
                SliceState(topology=parse_topology(t), slice_id=f"slice-{i}")
                for i, t in enumerate(topologies)
            ]
        )

    def admit(self, holder: str, topology: str) -> str | None:
        """Returns a slice_id, or None when no whole slice is free.

        Idempotent per holder: a holder re-admitting keeps its slice even
        when the requested topology differs (the elastic upgrade path
        goes through `upgrade`, which atomically swaps classes)."""
        want = parse_topology(topology)
        with self._lock:
            for s in self.slices:
                if s.held_by == holder:
                    return s.slice_id  # idempotent re-admission
            for s in self.slices:
                if s.held_by is None and not s.offline and s.matches(want):
                    s.held_by = holder
                    return s.slice_id
        return None

    def admit_many(self, holder: str, topology: str, n: int) -> list[str] | None:
        """Atomic N-slice admission (multi-slice jobs, spec.tpu.slices):
        grant the holder N whole free online slices of `topology`'s class
        or NOTHING — a partial hold would deadlock the fleet (two 2-slice
        jobs each holding one of three slices wait forever, and every
        1-slice waiter starves behind capacity nobody can use).

        Idempotent per holder: slices already held of the class count
        toward N (a re-admitting sync gets its ids back); a top-up to N is
        itself all-or-nothing. Returns the N slice_ids in inventory order,
        or None with no state change."""
        if n <= 1:
            sid = self.admit(holder, topology)
            return [sid] if sid is not None else None
        want = parse_topology(topology)
        with self._lock:
            held = [s for s in self.slices
                    if s.held_by == holder and s.matches(want)]
            if len(held) >= n:
                return [s.slice_id for s in held[:n]]
            free = [s for s in self.slices
                    if s.held_by is None and not s.offline and s.matches(want)]
            missing = n - len(held)
            if len(free) < missing:
                return None  # all-or-nothing: claim NOTHING
            for s in free[:missing]:
                s.held_by = holder
            return [s.slice_id for s in held] + [
                s.slice_id for s in free[:missing]]

    def free_of_class(self, topology: str) -> int:
        """Free ONLINE slice count of exactly `topology`'s class — what an
        N-slice admission needs >= N of."""
        want = parse_topology(topology)
        with self._lock:
            return sum(
                1 for s in self.slices
                if s.held_by is None and not s.offline and s.matches(want)
            )

    def upgrade(self, holder: str, topology: str) -> str | None:
        """Move the holder onto a slice of exactly `topology`'s class:
        returns the held slice when it already matches (and is online),
        else atomically claims a free online slice of the class and
        releases every other slice the holder had. None when no such
        slice is free — the holder keeps what it has. Only safe when the
        holder's gang is DRAINED (the released slice frees immediately);
        a live gang scaling up goes through `claim` + a deferred
        `release_except_class` once its old generation is gone."""
        want = parse_topology(topology)
        with self._lock:
            for s in self.slices:
                if s.held_by == holder and s.matches(want) and not s.offline:
                    return s.slice_id
            for s in self.slices:
                if s.held_by is None and not s.offline and s.matches(want):
                    for old in self.slices:
                        if old.held_by == holder:
                            old.held_by = None
                    s.held_by = holder
                    return s.slice_id
        return None

    def claim(self, holder: str, topology: str) -> str | None:
        """Claim a slice of `topology`'s class WITHOUT releasing anything
        else the holder has (idempotent when one is already held online).
        The hold-both half of a live scale-up: the old slice stays held —
        so no waiter can land on chips the old generation still occupies
        — until release_except_class frees it after the drain."""
        want = parse_topology(topology)
        with self._lock:
            for s in self.slices:
                if s.held_by == holder and s.matches(want) and not s.offline:
                    return s.slice_id
            for s in self.slices:
                if s.held_by is None and not s.offline and s.matches(want):
                    s.held_by = holder
                    return s.slice_id
        return None

    def held_slices(self, holder: str) -> list[str]:
        """Every slice_id the holder claims (a scale-up in flight holds
        two: the new full-class slice and the draining degraded one)."""
        with self._lock:
            return [s.slice_id for s in self.slices if s.held_by == holder]

    def release_except_class(self, holder: str, topology: str) -> bool:
        """Free every slice the holder claims whose class is NOT
        `topology`'s — the drain-complete half of a live scale-up. True
        when anything was actually freed (the caller then kicks
        waiters)."""
        want = parse_topology(topology)
        freed = False
        with self._lock:
            for s in self.slices:
                if s.held_by == holder and not s.matches(want):
                    s.held_by = None
                    freed = True
        return freed

    def holding(self, holder: str) -> str | None:
        """The slice_id the holder currently claims (online or offline),
        or None."""
        with self._lock:
            for s in self.slices:
                if s.held_by == holder:
                    return s.slice_id
        return None

    def holding_class(self, holder: str, topology: str) -> str | None:
        """The held slice matching `topology`'s class (online or offline;
        a read, never a claim), or None — how the controller names the
        authoritative slice while a scale-up briefly holds two."""
        want = parse_topology(topology)
        with self._lock:
            for s in self.slices:
                if s.held_by == holder and s.matches(want):
                    return s.slice_id
        return None

    def held_offline(self, holder: str) -> bool:
        """Does the holder's claim sit on a slice that has gone offline?
        (Capacity lost under a running gang: the claim survives until the
        controller releases it at the next roll/drain.)"""
        with self._lock:
            return any(
                s.held_by == holder and s.offline for s in self.slices
            )

    def set_capacity(self, count: int) -> list[str]:
        """Chaos/maintenance capacity dial: slices at inventory index >=
        `count` go offline (front of the inventory stays), slices below
        come back online. Held claims are NOT revoked — held_offline
        surfaces them. Returns the holders whose slices changed
        availability, so the controller can re-sync them."""
        affected: list[str] = []
        with self._lock:
            for i, s in enumerate(self.slices):
                off = i >= max(0, count)
                if off != s.offline:
                    s.offline = off
                    if s.held_by is not None:
                        affected.append(s.held_by)
        return affected

    def release(self, holder: str) -> bool:
        """Free the holder's slices; True if anything was actually held (so
        the controller can kick jobs waiting on slice admission instead of
        leaving them to the retry backoff)."""
        freed = False
        with self._lock:
            for s in self.slices:
                if s.held_by == holder:
                    s.held_by = None
                    freed = True
        return freed

    def free_slices(self) -> int:
        with self._lock:
            return sum(
                1 for s in self.slices
                if s.held_by is None and not s.offline
            )

    def free_by_class(self) -> dict[tuple[str, int], int]:
        """Free ONLINE slice count per capacity class (accelerator,
        num_chips) — the granularity `admit` matches on. The fleet
        scheduler simulates reservations for higher-ranked waiters
        against this view."""
        out: dict[tuple[str, int], int] = {}
        with self._lock:
            for s in self.slices:
                if s.held_by is None and not s.offline:
                    k = (s.topology.accelerator, s.topology.num_chips)
                    out[k] = out.get(k, 0) + 1
        return out

    def free_classes_below(self, topology: str) -> list[str]:
        """Degraded-admission candidates: canonical topology names
        ("v5e-2") of free online slice classes with the same accelerator
        and FEWER chips than `topology`, largest first — the order the
        elastic controller tries them in (least shrink wins)."""
        want = parse_topology(topology)
        seen: dict[int, str] = {}
        with self._lock:
            for s in self.slices:
                if (s.held_by is None and not s.offline
                        and s.topology.accelerator == want.accelerator
                        and s.topology.num_chips < want.num_chips):
                    seen.setdefault(s.topology.num_chips, s.topology.name)
        return [seen[c] for c in sorted(seen, reverse=True)]

    def snapshot(self) -> dict:
        """Inventory view for /debug/state: every slice's class, holder,
        and availability, plus the aggregate free count."""
        with self._lock:
            slices = [
                {
                    "slice_id": s.slice_id,
                    "topology": s.topology.name,
                    "held_by": s.held_by,
                    "offline": s.offline,
                }
                for s in self.slices
            ]
        return {
            "slices": slices,
            "total": len(slices),
            "free": sum(1 for s in slices
                        if s["held_by"] is None and not s["offline"]),
        }


def slice_class(topology: str) -> tuple[str, int]:
    """Capacity class of a topology request: (accelerator, chip count) —
    exactly the fields SliceAllocator.admit matches a free slice on."""
    t = parse_topology(topology)
    return (t.accelerator, t.num_chips)
